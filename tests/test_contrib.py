"""Contrib op tests (reference model: tests/python/unittest/test_contrib_*
— numpy cross-checks for box/ROI/misc ops, SURVEY §4)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.test_utils import assert_almost_equal


def _iou_np(a, b):
    iw = max(0.0, min(a[2], b[2]) - max(a[0], b[0]))
    ih = max(0.0, min(a[3], b[3]) - max(a[1], b[1]))
    inter = iw * ih
    ua = (a[2] - a[0]) * (a[3] - a[1]) + (b[2] - b[0]) * (b[3] - b[1]) - inter
    return inter / ua if ua > 0 else 0.0


def test_box_iou():
    lhs = nd.array([[0, 0, 2, 2], [1, 1, 3, 3]])
    rhs = nd.array([[0, 0, 2, 2], [10, 10, 11, 11], [1, 1, 2, 2]])
    out = nd.contrib.box_iou(lhs, rhs).asnumpy()
    assert out.shape == (2, 3)
    l, r = lhs.asnumpy(), rhs.asnumpy()
    for i in range(2):
        for j in range(3):
            assert abs(out[i, j] - _iou_np(l[i], r[j])) < 1e-5


def test_box_nms_basic():
    # rows: [cls, score, x1, y1, x2, y2]
    data = nd.array([[
        [0, 0.9, 0, 0, 10, 10],
        [0, 0.8, 1, 1, 11, 11],     # heavy overlap with row 0 → suppressed
        [0, 0.7, 20, 20, 30, 30],   # far away → kept
        [0, 0.05, 0, 0, 1, 1],      # below valid_thresh → dropped
    ]])
    out = nd.contrib.box_nms(data, overlap_thresh=0.5, valid_thresh=0.1,
                             coord_start=2, score_index=1,
                             id_index=0).asnumpy()[0]
    # survivors compacted to front, descending score
    assert out[0, 1] == pytest.approx(0.9)
    assert out[1, 1] == pytest.approx(0.7)
    assert np.all(out[2] == -1) and np.all(out[3] == -1)


def test_box_nms_class_aware():
    # same boxes, different classes: not suppressed unless force_suppress
    data = nd.array([[
        [0, 0.9, 0, 0, 10, 10],
        [1, 0.8, 1, 1, 11, 11],
    ]])
    out = nd.contrib.box_nms(data, overlap_thresh=0.5, id_index=0,
                             force_suppress=False).asnumpy()[0]
    assert (out[:, 1] > 0).sum() == 2
    out2 = nd.contrib.box_nms(data, overlap_thresh=0.5, id_index=0,
                              force_suppress=True).asnumpy()[0]
    assert (out2[:, 1] > 0).sum() == 1


def test_bipartite_matching():
    w = nd.array([[[0.9, 0.1], [0.8, 0.85], [0.1, 0.2]]])  # (1, 3, 2)
    row, col = nd.contrib.bipartite_matching(w, threshold=0.5)
    row, col = row.asnumpy()[0], col.asnumpy()[0]
    # greedy: (0,0)=0.9 first, then (1,1)=0.85; row 2 unmatched
    assert row.tolist() == [0, 1, -1]
    assert col.tolist() == [0, 1]


def test_multibox_prior():
    x = nd.zeros((1, 3, 4, 4))
    anchors = nd.contrib.MultiBoxPrior(x, sizes=[0.5, 0.25],
                                       ratios=[1, 2]).asnumpy()
    assert anchors.shape == (1, 4 * 4 * 3, 4)
    a = anchors[0].reshape(4, 4, 3, 4)
    # first anchor at cell (0,0): size .5, ratio 1 → centered at (.125,.125)
    c = a[0, 0, 0]
    assert np.allclose((c[0] + c[2]) / 2, 0.125, atol=1e-6)
    assert np.allclose((c[1] + c[3]) / 2, 0.125, atol=1e-6)
    assert np.allclose(c[3] - c[1], 0.5, atol=1e-6)  # height = size


def test_multibox_target_and_detection():
    anchors = nd.array([[[0.0, 0.0, 0.4, 0.4],
                         [0.5, 0.5, 1.0, 1.0],
                         [0.0, 0.6, 0.3, 0.9]]])  # (1, 3, 4)
    # one gt box overlapping anchor 1
    label = nd.array([[[1.0, 0.52, 0.52, 0.98, 0.98]]])  # (B=1, M=1, 5)
    cls_pred = nd.zeros((1, 3, 3))  # (B, num_cls+1, N)
    loc_t, loc_m, cls_t = nd.contrib.MultiBoxTarget(
        anchors, label, cls_pred, overlap_threshold=0.5)
    assert loc_t.shape == (1, 12) and loc_m.shape == (1, 12)
    ct = cls_t.asnumpy()[0]
    assert ct[1] == 2.0  # gt class 1 → target 2 (bg is 0)
    assert ct[0] == 0.0 and ct[2] == 0.0
    lm = loc_m.asnumpy()[0].reshape(3, 4)
    assert np.all(lm[1] == 1.0) and np.all(lm[0] == 0.0)

    # detection: perfect loc_pred of zeros decodes anchors themselves
    cls_prob = nd.array([[[0.1, 0.8, 0.2], [0.1, 0.1, 0.7],
                          [0.8, 0.1, 0.1]]])  # (B, 3 cls, N)
    loc_pred = nd.zeros((1, 12))
    out = nd.contrib.MultiBoxDetection(cls_prob, loc_pred, anchors,
                                       nms_threshold=0.5).asnumpy()[0]
    assert out.shape == (3, 6)
    kept = out[out[:, 0] >= 0]
    assert len(kept) >= 1


def test_roi_align_shapes_and_values():
    # ramp image: value = y → averaging across a roi gives its center y
    h = w = 8
    img = np.tile(np.arange(h, dtype=np.float32)[:, None], (1, w))
    data = nd.array(img[None, None])  # (1, 1, 8, 8)
    rois = nd.array([[0, 0, 0, 7, 7]])  # whole image
    out = nd.contrib.ROIAlign(data, rois, pooled_size=(2, 2),
                              spatial_scale=1.0, sample_ratio=2)
    o = out.asnumpy()
    assert o.shape == (1, 1, 2, 2)
    # rows of the 2x2 pool: lower/upper half mean of the ramp
    assert o[0, 0, 0, 0] < o[0, 0, 1, 0]
    assert np.allclose(o[0, 0, 0, 0], o[0, 0, 0, 1], atol=1e-5)


def test_roi_align_grad_flows():
    from mxnet_tpu import autograd
    data = nd.random.uniform(shape=(1, 2, 6, 6))
    data.attach_grad()
    rois = nd.array([[0, 1, 1, 4, 4]])
    with autograd.record():
        out = nd.contrib.ROIAlign(data, rois, pooled_size=(2, 2),
                                  spatial_scale=1.0)
        loss = out.sum()
    loss.backward()
    assert float(nd.abs(data.grad).sum().asscalar()) > 0


def test_roi_pooling():
    img = np.arange(16, dtype=np.float32).reshape(4, 4)
    data = nd.array(img[None, None])
    rois = nd.array([[0, 0, 0, 3, 3]])
    out = nd.ROIPooling(data, rois, pooled_size=(2, 2), spatial_scale=1.0)
    o = out.asnumpy()[0, 0]
    # max of each quadrant
    assert o.tolist() == [[5.0, 7.0], [13.0, 15.0]]


def test_proposal():
    b, a, h, w = 1, 6, 4, 4  # a = len(scales) * len(ratios)
    rng = np.random.RandomState(0)
    cls_prob = nd.array(rng.uniform(size=(b, 2 * a, h, w)))
    bbox_pred = nd.array(rng.uniform(-0.1, 0.1, size=(b, 4 * a, h, w)))
    im_info = nd.array([[64, 64, 1.0]])
    rois = nd.contrib.Proposal(cls_prob, bbox_pred, im_info,
                               rpn_pre_nms_top_n=12, rpn_post_nms_top_n=5,
                               feature_stride=16, scales=(2, 4),
                               ratios=(0.5, 1, 2))
    o = rois.asnumpy()
    assert o.shape == (5, 5)
    assert np.all(o[:, 0] == 0)  # batch index
    assert np.all(o[:, 1:] >= 0) and np.all(o[:, 1:] <= 63)


def test_bilinear_resize_2d():
    x = nd.array(np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4))
    out = nd.contrib.BilinearResize2D(x, height=7, width=7)
    o = out.asnumpy()[0, 0]
    assert o.shape == (7, 7)
    # align_corners=True keeps the corners exact
    assert o[0, 0] == pytest.approx(0.0)
    assert o[6, 6] == pytest.approx(15.0)


def test_adaptive_avg_pooling_2d():
    x = nd.random.uniform(shape=(2, 3, 7, 5))
    out = nd.contrib.AdaptiveAvgPooling2D(x, output_size=(2, 2))
    assert out.shape == (2, 3, 2, 2)
    one = nd.contrib.AdaptiveAvgPooling2D(x, output_size=1).asnumpy()
    assert_almost_equal(one[..., 0, 0], x.asnumpy().mean(axis=(2, 3)),
                        rtol=1e-5, atol=1e-6)


def test_quadratic_and_grad():
    from mxnet_tpu import autograd
    x = nd.array([1.0, 2.0, 3.0])
    x.attach_grad()
    with autograd.record():
        y = nd.contrib.quadratic(x, a=1.0, b=2.0, c=3.0)
    y.backward()
    assert_almost_equal(y, np.array([6.0, 11.0, 18.0]))
    assert_almost_equal(x.grad, 2 * x.asnumpy() + 2)


def test_index_array_allclose_arange_like():
    x = nd.zeros((2, 3))
    ia = nd.contrib.index_array(x).asnumpy()
    assert ia.shape == (2, 3, 2)
    assert ia[1, 2].tolist() == [1, 2]
    a = nd.array([1.0, 2.0])
    assert nd.contrib.allclose(a, a).asscalar() == 1.0
    assert nd.contrib.allclose(a, a + 1).asscalar() == 0.0
    al = nd.contrib.arange_like(nd.zeros((3, 4)), start=1, axis=1).asnumpy()
    assert al.tolist() == [1, 2, 3, 4]


def test_index_copy():
    old = nd.zeros((4, 2))
    new = nd.array([[1.0, 1], [2, 2]])
    idx = nd.array([3, 0])
    out = nd.contrib.index_copy(old, idx, new).asnumpy()
    assert out[3].tolist() == [1, 1] and out[0].tolist() == [2, 2]
    assert np.all(out[[1, 2]] == 0)


def test_gradientmultiplier():
    from mxnet_tpu import autograd
    x = nd.array([1.0, 2.0])
    x.attach_grad()
    with autograd.record():
        y = nd.contrib.gradientmultiplier(x, scalar=0.5).sum()
    y.backward()
    assert_almost_equal(x.grad, np.array([0.5, 0.5]))


def test_fft_ifft_roundtrip():
    x = nd.random.uniform(shape=(2, 8))
    f = nd.contrib.fft(x)
    assert f.shape == (2, 16)
    back = nd.contrib.ifft(f) / 8
    assert_almost_equal(back, x.asnumpy(), rtol=1e-4, atol=1e-5)


def test_amp_cast_multicast():
    x = nd.array([1.0, 2.0])
    y = nd.amp_cast(x, dtype="float16")
    assert y.dtype == np.float16
    a16 = nd.array([1.0], dtype="float16")
    b32 = nd.array([2.0], dtype="float32")
    oa, ob = nd.amp_multicast(a16, b32, num_outputs=2)
    assert oa.dtype == np.float32 and ob.dtype == np.float32


def test_contrib_symbol_path():
    import mxnet_tpu.symbol as sym
    x = sym.var("x")
    y = sym.contrib.quadratic(x, a=1.0, b=0.0, c=1.0)
    ex = y.bind(mx.cpu(), {"x": nd.array([2.0])})
    out = ex.forward()[0].asnumpy()
    assert out.tolist() == [5.0]


def test_box_decode_encode():
    anchors = nd.array([[[0.2, 0.2, 0.4, 0.4]]])  # corner (1, 1, 4)
    zeros = nd.zeros((1, 1, 4))
    out = nd.contrib.box_decode(zeros, anchors, format="corner").asnumpy()
    assert np.allclose(out[0, 0], [0.2, 0.2, 0.4, 0.4], atol=1e-6)
    samples = nd.ones((1, 1))
    matches = nd.zeros((1, 1))
    refs = nd.array([[[0.2, 0.2, 0.4, 0.4]]])
    t, m = nd.contrib.box_encode(samples, matches, anchors, refs)
    assert np.allclose(t.asnumpy(), 0.0, atol=1e-5)
    assert np.all(m.asnumpy() == 1.0)


def test_deformable_convolution_zero_offset_matches_conv():
    # zero offsets + no modulation => identical to a plain dilated conv
    np.random.seed(0)
    b, c, h, w = 2, 4, 9, 9
    o, kh, kw = 6, 3, 3
    x = nd.array(np.random.randn(b, c, h, w).astype(np.float32))
    wt = nd.array(np.random.randn(o, c, kh, kw).astype(np.float32) * 0.1)
    bs = nd.array(np.random.randn(o).astype(np.float32))
    oh = ow = h - 2  # stride 1, pad 0, dilate 1
    off = nd.zeros((b, 2 * kh * kw, oh, ow))
    y_def = nd.contrib.DeformableConvolution(
        x, off, wt, bs, kernel=(kh, kw), num_filter=o)
    y_ref = nd.Convolution(x, wt, bs, kernel=(kh, kw), num_filter=o)
    assert_almost_equal(y_def, y_ref, rtol=1e-4, atol=1e-4)


def test_deformable_convolution_pad_stride_groups():
    np.random.seed(1)
    b, c, h, w = 2, 4, 8, 8
    o, kh, kw = 4, 3, 3
    x = nd.array(np.random.randn(b, c, h, w).astype(np.float32))
    wt = nd.array(np.random.randn(o, c // 2, kh, kw).astype(np.float32) * 0.1)
    oh = ow = 4  # stride 2, pad 1
    off = nd.zeros((b, 2 * 2 * kh * kw, oh, ow))  # 2 deformable groups
    y_def = nd.contrib.DeformableConvolution(
        x, off, wt, kernel=(kh, kw), stride=(2, 2), pad=(1, 1),
        num_filter=o, num_group=2, num_deformable_group=2, no_bias=True)
    y_ref = nd.Convolution(x, wt, kernel=(kh, kw), stride=(2, 2),
                           pad=(1, 1), num_filter=o, num_group=2,
                           no_bias=True)
    assert_almost_equal(y_def, y_ref, rtol=1e-4, atol=1e-4)


def test_deformable_convolution_offset_shifts_samples():
    # integer offset (0.0, 1.0) on every tap == shifting the input left
    np.random.seed(2)
    b, c, h, w = 1, 2, 7, 9
    o, kh, kw = 3, 3, 3
    x_np = np.random.randn(b, c, h, w).astype(np.float32)
    wt = nd.array(np.random.randn(o, c, kh, kw).astype(np.float32) * 0.1)
    oh, ow = h - 2, w - 2
    off_np = np.zeros((b, 2 * kh * kw, oh, ow), np.float32)
    off_np[:, 1::2] = 1.0  # x-offsets = +1
    y_def = nd.contrib.DeformableConvolution(
        nd.array(x_np), nd.array(off_np), wt, kernel=(kh, kw),
        num_filter=o, no_bias=True)
    x_shift = np.zeros_like(x_np)
    x_shift[..., :-1] = x_np[..., 1:]
    y_ref = nd.Convolution(nd.array(x_shift), wt, kernel=(kh, kw),
                           num_filter=o, no_bias=True)
    # interior columns agree exactly (boundary column differs: zero pad)
    assert_almost_equal(y_def.asnumpy()[..., :-1], y_ref.asnumpy()[..., :-1],
                        rtol=1e-4, atol=1e-4)


def test_deformable_convolution_numeric_gradient():
    from mxnet_tpu.test_utils import check_numeric_gradient

    np.random.seed(3)
    b, c, h, w = 1, 2, 5, 5
    o, kh, kw = 2, 3, 3
    oh = ow = 3
    x = np.random.randn(b, c, h, w)
    off = np.random.uniform(-0.4, 0.4, (b, 2 * kh * kw, oh, ow))
    wt = np.random.randn(o, c, kh, kw) * 0.3

    def f(xx, oo, ww):
        return nd.contrib.DeformableConvolution(
            xx, oo, ww, kernel=(kh, kw), num_filter=o, no_bias=True)

    check_numeric_gradient(f, [x, off, wt], eps=1e-4, rtol=2e-2, atol=2e-3)


def test_modulated_deformable_convolution():
    np.random.seed(4)
    b, c, h, w = 2, 3, 7, 7
    o, kh, kw = 4, 3, 3
    oh = ow = 5
    x = nd.array(np.random.randn(b, c, h, w).astype(np.float32))
    wt = nd.array(np.random.randn(o, c, kh, kw).astype(np.float32) * 0.1)
    off = nd.zeros((b, 2 * kh * kw, oh, ow))
    # mask of ones => DCNv1 behaviour
    ones = nd.ones((b, kh * kw, oh, ow))
    y_mod = nd.contrib.ModulatedDeformableConvolution(
        x, off, ones, wt, kernel=(kh, kw), num_filter=o, no_bias=True)
    y_ref = nd.Convolution(x, wt, kernel=(kh, kw), num_filter=o,
                           no_bias=True)
    assert_almost_equal(y_mod, y_ref, rtol=1e-4, atol=1e-4)
    # half mask scales contributions linearly
    y_half = nd.contrib.ModulatedDeformableConvolution(
        x, off, ones * 0.5, wt, kernel=(kh, kw), num_filter=o, no_bias=True)
    assert_almost_equal(y_half, y_ref * 0.5, rtol=1e-4, atol=1e-4)


def test_with_seed_decorator():
    from mxnet_tpu.test_utils import with_seed

    vals = []

    @with_seed(42)
    def gen():
        vals.append(np.random.randint(0, 10 ** 9))

    gen()
    gen()
    assert vals[0] == vals[1]


def test_interleaved_attention_bf16_grads_match_f32():
    """The interleaved attention pair's dtype-preserving custom vjps
    (r4): bf16 input gradients must match the f32 oracle within bf16
    rounding — the backward einsums stay low-precision instead of the
    pet+astype pattern's f32xf32."""
    import numpy as np

    rs = np.random.RandomState(0)
    qkv_np = rs.randn(6, 2, 3 * 8).astype(np.float32)

    from mxnet_tpu import autograd

    def grad_of(dtype):
        qkv = nd.array(qkv_np).astype(dtype)
        qkv.attach_grad()
        with autograd.record():
            att = nd.softmax(
                nd.interleaved_matmul_selfatt_qk(qkv, heads=2), axis=-1)
            out = nd.interleaved_matmul_selfatt_valatt(qkv, att, heads=2)
            loss = (out.astype("float32") ** 2).sum()
        loss.backward()
        return qkv.grad.asnumpy().astype(np.float32)

    g32 = grad_of("float32")
    gb = grad_of("bfloat16")
    rel = np.abs(g32 - gb).max() / (np.abs(g32).max() + 1e-9)
    assert rel < 0.03, rel
