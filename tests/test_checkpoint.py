"""Atomic checkpoint/resume tests (SURVEY §5 checkpoint-resume, D10 —
beyond the reference's do_checkpoint+restart posture).

Key invariant: crash-resume-continue training produces EXACTLY the same
weights as uninterrupted training (momentum optimizer forces the trainer
state to matter)."""
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, checkpoint, gluon, nd
from mxnet_tpu.test_utils import assert_almost_equal


def _net():
    mx.random.seed(0)
    net = gluon.nn.Dense(4)
    net.initialize(mx.init.Xavier())
    net(nd.ones((2, 6)))
    return net


def _step(net, trainer, seed):
    rs = np.random.RandomState(seed)
    x = nd.array(rs.randn(2, 6).astype(np.float32))
    with autograd.record():
        loss = (net(x) ** 2).mean()
    loss.backward()
    trainer.step(2)


def test_crash_resume_matches_uninterrupted(tmp_path):
    ckpt = str(tmp_path / "ckpts")

    # uninterrupted: 4 steps
    net_a = _net()
    tr_a = gluon.Trainer(net_a.collect_params(), "sgd",
                         {"learning_rate": 0.1, "momentum": 0.9})
    for s in range(4):
        _step(net_a, tr_a, s)

    # interrupted: 2 steps, checkpoint, "crash", resume into NEW objects
    net_b = _net()
    tr_b = gluon.Trainer(net_b.collect_params(), "sgd",
                         {"learning_rate": 0.1, "momentum": 0.9})
    for s in range(2):
        _step(net_b, tr_b, s)
    checkpoint.save_checkpoint(ckpt, 2, net_b, tr_b)
    del net_b, tr_b

    net_c = _net()  # fresh init (different weights until resume)
    tr_c = gluon.Trainer(net_c.collect_params(), "sgd",
                         {"learning_rate": 0.1, "momentum": 0.9})
    tr_c._init_kvstore()  # materialise state slots before load
    step, extra = checkpoint.resume(ckpt, net_c, tr_c)
    assert step == 2
    for s in range(2, 4):
        _step(net_c, tr_c, s)
    assert_almost_equal(net_c.weight.data(), net_a.weight.data(),
                        rtol=1e-6, atol=1e-7)


def test_latest_ignores_torn_and_foreign(tmp_path):
    ckpt = str(tmp_path / "ckpts")
    net = _net()
    checkpoint.save_checkpoint(ckpt, 1, net)
    checkpoint.save_checkpoint(ckpt, 5, net)
    os.makedirs(os.path.join(ckpt, "ckpt-9"))       # torn: no manifest
    os.makedirs(os.path.join(ckpt, ".tmp-7-123"))   # stale tmp
    os.makedirs(os.path.join(ckpt, "ckpt-bogus"))   # unparseable
    assert checkpoint.latest_checkpoint(ckpt).endswith("ckpt-5")
    step, _ = checkpoint.resume(ckpt, _net())
    assert step == 5


def test_prune_keeps_newest(tmp_path):
    ckpt = str(tmp_path / "ckpts")
    net = _net()
    for s in (1, 2, 3, 4, 5):
        checkpoint.save_checkpoint(ckpt, s, net)
    checkpoint.prune_checkpoints(ckpt, keep=2)
    steps = sorted(int(n[5:]) for n in os.listdir(ckpt)
                   if n.startswith("ckpt-"))
    assert steps == [4, 5]


def test_save_with_keep_autoprunes(tmp_path):
    ckpt = str(tmp_path / "ckpts")
    net = _net()
    for s in (1, 2, 3):
        checkpoint.save_checkpoint(ckpt, s, net, keep=2)
    steps = sorted(int(n[5:]) for n in os.listdir(ckpt)
                   if n.startswith("ckpt-"))
    assert steps == [2, 3]


def test_resume_empty_dir_returns_zero(tmp_path):
    step, extra = checkpoint.resume(str(tmp_path / "none"), _net())
    assert step == 0 and extra == {}


def test_extra_payload_roundtrip(tmp_path):
    ckpt = str(tmp_path / "ckpts")
    net = _net()
    checkpoint.save_checkpoint(ckpt, 3, net,
                               extra={"epoch": 3, "lr": 0.01})
    step, extra = checkpoint.resume(ckpt, _net())
    assert step == 3
    assert extra == {"epoch": 3, "lr": 0.01}


def test_estimator_fault_tolerant_handler(tmp_path):
    from mxnet_tpu.gluon.contrib.estimator import (Estimator,
                                                   FaultTolerantCheckpoint)
    from mxnet_tpu.gluon.data import ArrayDataset, DataLoader

    ckpt = str(tmp_path / "est")
    rs = np.random.RandomState(0)
    x = rs.randn(16, 6).astype(np.float32)
    y = rs.randint(0, 4, 16).astype(np.float32)

    def fit_once(epochs=2):
        net = _net()
        est = Estimator(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                        trainer=gluon.Trainer(net.collect_params(), "sgd",
                                              {"learning_rate": 0.05}))
        handler = FaultTolerantCheckpoint(ckpt, save_every=1)
        loader = DataLoader(ArrayDataset(nd.array(x), nd.array(y)),
                            batch_size=8)
        est.fit(loader, epochs=epochs, event_handlers=[handler])
        return net, handler

    _net1, h1 = fit_once()
    assert h1.resumed_epoch == 0
    assert checkpoint.latest_checkpoint(ckpt) is not None
    # second run resumes from the first run's checkpoints; epochs=2 is a
    # TOTAL budget, so the resumed run trains zero additional epochs —
    # rerunning an interrupted job never overshoots the original budget
    _net2, h2 = fit_once()
    assert h2.resumed_epoch == 2
    assert h2._epoch == 2, "resumed fit overshot the epoch budget"
    _, path = checkpoint._complete_checkpoints(ckpt)[-1]
    assert path.endswith("ckpt-2")
    # a LARGER budget resumes at 2 and trains exactly one more epoch
    _net3, h3 = fit_once(epochs=3)
    assert h3.resumed_epoch == 2 and h3._epoch == 3


def test_sharded_checkpoint_roundtrip_preserves_sharding(tmp_path):
    """sharded=True routes weights through orbax/tensorstore: values AND
    dp/tp shardings survive resume without a host-side gather."""
    import jax

    from mxnet_tpu import parallel

    mesh = parallel.make_mesh({"dp": 4, "tp": 2})
    with parallel.mesh_scope(mesh):
        mx.random.seed(9)
        net = _net()
        x = nd.ones((2, 6))
        net(x)
        parallel.replicate_block_params(net)
        parallel.shard_param(net.weight, ("tp", None))
        want = {k: p.data().asnumpy()
                for k, p in net._collect_params_with_prefix().items()}

        d = str(tmp_path / "sharded")
        checkpoint.save_checkpoint(d, 7, net, sharded=True)

        mx.random.seed(10)  # different init: resume must overwrite it
        net2 = _net()
        net2(x)
        parallel.replicate_block_params(net2)
        parallel.shard_param(net2.weight, ("tp", None))
        step, _ = checkpoint.resume(d, net2)
        assert step == 7
        for k, p in net2._collect_params_with_prefix().items():
            np.testing.assert_allclose(p.data().asnumpy(), want[k],
                                       rtol=1e-6)
        sh = net2.weight.data()._data.sharding
        assert "tp" in str(getattr(sh, "spec", "")), sh
