"""Atomic checkpoint/resume tests (SURVEY §5 checkpoint-resume, D10 —
beyond the reference's do_checkpoint+restart posture).

Key invariant: crash-resume-continue training produces EXACTLY the same
weights as uninterrupted training (momentum optimizer forces the trainer
state to matter)."""
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, checkpoint, gluon, nd
from mxnet_tpu.test_utils import assert_almost_equal


def _net():
    mx.random.seed(0)
    net = gluon.nn.Dense(4)
    net.initialize(mx.init.Xavier())
    net(nd.ones((2, 6)))
    return net


def _step(net, trainer, seed):
    rs = np.random.RandomState(seed)
    x = nd.array(rs.randn(2, 6).astype(np.float32))
    with autograd.record():
        loss = (net(x) ** 2).mean()
    loss.backward()
    trainer.step(2)


def test_crash_resume_matches_uninterrupted(tmp_path):
    ckpt = str(tmp_path / "ckpts")

    # uninterrupted: 4 steps
    net_a = _net()
    tr_a = gluon.Trainer(net_a.collect_params(), "sgd",
                         {"learning_rate": 0.1, "momentum": 0.9})
    for s in range(4):
        _step(net_a, tr_a, s)

    # interrupted: 2 steps, checkpoint, "crash", resume into NEW objects
    net_b = _net()
    tr_b = gluon.Trainer(net_b.collect_params(), "sgd",
                         {"learning_rate": 0.1, "momentum": 0.9})
    for s in range(2):
        _step(net_b, tr_b, s)
    checkpoint.save_checkpoint(ckpt, 2, net_b, tr_b)
    del net_b, tr_b

    net_c = _net()  # fresh init (different weights until resume)
    tr_c = gluon.Trainer(net_c.collect_params(), "sgd",
                         {"learning_rate": 0.1, "momentum": 0.9})
    tr_c._init_kvstore()  # materialise state slots before load
    step, extra = checkpoint.resume(ckpt, net_c, tr_c)
    assert step == 2
    for s in range(2, 4):
        _step(net_c, tr_c, s)
    assert_almost_equal(net_c.weight.data(), net_a.weight.data(),
                        rtol=1e-6, atol=1e-7)


def test_latest_ignores_torn_and_foreign(tmp_path):
    ckpt = str(tmp_path / "ckpts")
    net = _net()
    checkpoint.save_checkpoint(ckpt, 1, net)
    checkpoint.save_checkpoint(ckpt, 5, net)
    os.makedirs(os.path.join(ckpt, "ckpt-9"))       # torn: no manifest
    os.makedirs(os.path.join(ckpt, ".tmp-7-123"))   # stale tmp
    os.makedirs(os.path.join(ckpt, "ckpt-bogus"))   # unparseable
    assert checkpoint.latest_checkpoint(ckpt).endswith("ckpt-5")
    step, _ = checkpoint.resume(ckpt, _net())
    assert step == 5


def test_prune_keeps_newest(tmp_path):
    ckpt = str(tmp_path / "ckpts")
    net = _net()
    for s in (1, 2, 3, 4, 5):
        checkpoint.save_checkpoint(ckpt, s, net)
    checkpoint.prune_checkpoints(ckpt, keep=2)
    steps = sorted(int(n[5:]) for n in os.listdir(ckpt)
                   if n.startswith("ckpt-"))
    assert steps == [4, 5]


def test_save_with_keep_autoprunes(tmp_path):
    ckpt = str(tmp_path / "ckpts")
    net = _net()
    for s in (1, 2, 3):
        checkpoint.save_checkpoint(ckpt, s, net, keep=2)
    steps = sorted(int(n[5:]) for n in os.listdir(ckpt)
                   if n.startswith("ckpt-"))
    assert steps == [2, 3]


def test_resume_empty_dir_returns_zero(tmp_path):
    step, extra = checkpoint.resume(str(tmp_path / "none"), _net())
    assert step == 0 and extra == {}


def test_extra_payload_roundtrip(tmp_path):
    ckpt = str(tmp_path / "ckpts")
    net = _net()
    checkpoint.save_checkpoint(ckpt, 3, net,
                               extra={"epoch": 3, "lr": 0.01})
    step, extra = checkpoint.resume(ckpt, _net())
    assert step == 3
    assert extra == {"epoch": 3, "lr": 0.01}


def test_estimator_fault_tolerant_handler(tmp_path):
    from mxnet_tpu.gluon.contrib.estimator import (Estimator,
                                                   FaultTolerantCheckpoint)
    from mxnet_tpu.gluon.data import ArrayDataset, DataLoader

    ckpt = str(tmp_path / "est")
    rs = np.random.RandomState(0)
    x = rs.randn(16, 6).astype(np.float32)
    y = rs.randint(0, 4, 16).astype(np.float32)

    def fit_once(epochs=2):
        net = _net()
        est = Estimator(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                        trainer=gluon.Trainer(net.collect_params(), "sgd",
                                              {"learning_rate": 0.05}))
        handler = FaultTolerantCheckpoint(ckpt, save_every=1)
        loader = DataLoader(ArrayDataset(nd.array(x), nd.array(y)),
                            batch_size=8)
        est.fit(loader, epochs=epochs, event_handlers=[handler])
        return net, handler

    _net1, h1 = fit_once()
    assert h1.resumed_epoch == 0
    assert checkpoint.latest_checkpoint(ckpt) is not None
    # second run resumes from the first run's checkpoints; epochs=2 is a
    # TOTAL budget, so the resumed run trains zero additional epochs —
    # rerunning an interrupted job never overshoots the original budget
    _net2, h2 = fit_once()
    assert h2.resumed_epoch == 2
    assert h2._epoch == 2, "resumed fit overshot the epoch budget"
    _, path = checkpoint._complete_checkpoints(ckpt)[-1]
    assert path.endswith("ckpt-2")
    # a LARGER budget resumes at 2 and trains exactly one more epoch
    _net3, h3 = fit_once(epochs=3)
    assert h3.resumed_epoch == 2 and h3._epoch == 3


# --- async checkpointing (round 6) ------------------------------------------

def _trainer(net):
    return gluon.Trainer(net.collect_params(), "sgd",
                         {"learning_rate": 0.1, "momentum": 0.9})


def test_async_save_byte_identical_to_sync(tmp_path):
    """The overlapped writer must produce EXACTLY the bytes the sync
    path produces — same members, same container encoding — so a drain
    or chaos resume can't tell which path wrote its checkpoint."""
    net = _net()
    tr = _trainer(net)
    _step(net, tr, 0)
    p_sync = checkpoint.save_checkpoint(str(tmp_path / "sync"), 1, net, tr,
                                        extra={"epoch": 1})
    ticket = checkpoint.save_checkpoint_async(str(tmp_path / "async"), 1,
                                              net, tr, extra={"epoch": 1})
    p_async = ticket.result(60)
    assert ticket.done() and ticket.step == 1
    for member in ("model.params", "trainer.states", "rng.npy"):
        with open(os.path.join(p_sync, member), "rb") as a, \
                open(os.path.join(p_async, member), "rb") as b:
            assert a.read() == b.read(), member
    step, extra = checkpoint.resume(str(tmp_path / "async"), _net())
    assert step == 1 and extra == {"epoch": 1}


def test_async_save_returns_before_write(tmp_path, monkeypatch):
    """save() must come back after the (synchronous) snapshot even while
    the write is stalled — the overlap claim, proven with a gated writer
    rather than a timing assertion."""
    import threading

    gate = threading.Event()
    real_write = checkpoint._write_snapshot

    def gated(tmp, snap):
        gate.wait(60)
        real_write(tmp, snap)

    monkeypatch.setattr(checkpoint, "_write_snapshot", gated)
    net = _net()
    ckpt = checkpoint.AsyncCheckpointer()
    try:
        ticket = ckpt.save(str(tmp_path / "c"), 1, net)  # returns gated
        assert not ticket.done()
        assert checkpoint.latest_checkpoint(str(tmp_path / "c")) is None
        gate.set()
        path = ticket.result(60)
        assert path.endswith("ckpt-1")
    finally:
        gate.set()
        ckpt.close()


def test_async_backpressure_blocks_at_max_pending(tmp_path, monkeypatch):
    """max_pending bounds host snapshots: the save PAST the bound waits
    for the oldest write instead of queueing unboundedly."""
    import threading

    gate = threading.Event()
    real_write = checkpoint._write_snapshot
    monkeypatch.setattr(checkpoint, "_write_snapshot",
                        lambda tmp, snap: (gate.wait(60),
                                           real_write(tmp, snap)))
    net = _net()
    ckpt = checkpoint.AsyncCheckpointer(max_pending=1)
    try:
        ckpt.save(str(tmp_path / "c"), 1, net)
        done = threading.Event()

        def second():
            ckpt.save(str(tmp_path / "c"), 2, net)
            done.set()

        t = threading.Thread(target=second, daemon=True)
        t.start()
        assert not done.wait(0.3), "save #2 ignored the pending bound"
        gate.set()
        assert done.wait(60)
        t.join()
        ckpt.wait(60)
    finally:
        gate.set()
        ckpt.close()
    assert checkpoint.latest_checkpoint(str(tmp_path / "c")).endswith("ckpt-2")


def test_async_writer_crash_leaves_prior_checkpoint_loadable(
        tmp_path, monkeypatch):
    """Satellite (c): kill the writer mid-write.  The failed step's
    staging dir is cleaned up, the error surfaces loudly (ticket AND the
    next save), and the previous complete checkpoint still resumes."""
    from mxnet_tpu.base import MXNetError

    ckpt_dir = str(tmp_path / "c")
    net = _net()
    tr = _trainer(net)
    _step(net, tr, 0)
    checkpoint.save_checkpoint(ckpt_dir, 1, net, tr)

    def boom(tmp, snap):
        raise OSError("disk gone")

    monkeypatch.setattr(checkpoint, "_write_snapshot", boom)
    ckpt = checkpoint.AsyncCheckpointer()
    ticket = ckpt.save(ckpt_dir, 2, net, tr)
    with pytest.raises(OSError, match="disk gone"):
        ticket.result(60)
    with pytest.raises(MXNetError, match="previous async checkpoint"):
        ckpt.save(ckpt_dir, 3, net, tr)  # fire-and-forget still fails loudly
    assert not [n for n in os.listdir(ckpt_dir) if n.startswith(".tmp-")]

    net2 = _net()
    tr2 = _trainer(net2)
    tr2._init_kvstore()
    step, _ = checkpoint.resume(ckpt_dir, net2, tr2)
    assert step == 1
    assert_almost_equal(net2.weight.data(), net.weight.data(),
                        rtol=0, atol=0)


def test_async_counters_land_in_step_record(tmp_path):
    """Tentpole telemetry: ckpt.save / ckpt.bytes / ckpt.async_overlap_ms
    ride the per-step JSONL record, with the write overlapping the open
    step window (the background span lands in the CURRENT step)."""
    from mxnet_tpu import telemetry

    path = str(tmp_path / "t.jsonl")
    telemetry.enable(jsonl_path=path)
    try:
        net = _net()
        tr = _trainer(net)
        with telemetry.step():
            _step(net, tr, 0)
            t = checkpoint.save_checkpoint_async(str(tmp_path / "c"), 1,
                                                 net, tr)
            t.result(60)
    finally:
        telemetry.disable()
    rec = telemetry.read_jsonl(path)[0]
    assert rec["ckpt_saves"] == 1
    assert rec["ckpt_bytes"] > 0
    assert rec["ckpt_async_overlap_ms"] > 0
    assert rec["phases_ms"].get("ckpt.snapshot", 0) > 0
    assert rec["phases_ms"].get("ckpt.write", 0) > 0


# --- preemption drain (round 6) ---------------------------------------------

def test_drain_checkpoint_and_exit(tmp_path):
    """request_drain → drain_checkpoint_and_exit flushes the async
    writer, cuts a final sync checkpoint, and exits with the preemption
    status the launcher budgets separately."""
    from mxnet_tpu.gluon import trainer as trainer_mod

    ckpt_dir = str(tmp_path / "c")
    net = _net()
    tr = _trainer(net)
    _step(net, tr, 0)
    checkpoint.save_checkpoint_async(ckpt_dir, 1, net, tr)
    trainer_mod.request_drain()
    try:
        assert trainer_mod.drain_requested()
        assert trainer_mod.drain_consensus()  # single-process degenerate
        with pytest.raises(SystemExit) as e:
            checkpoint.drain_checkpoint_and_exit(ckpt_dir, 2, net, tr)
        assert e.value.code == trainer_mod.PREEMPTED_EXIT_CODE == 75
    finally:
        trainer_mod.reset_drain()
    assert checkpoint.latest_checkpoint(ckpt_dir).endswith("ckpt-2")
    step, _ = checkpoint.resume(ckpt_dir, _net())
    assert step == 2


# --- torn-state hardening (round 6 satellites a+b) --------------------------

def test_resume_sweeps_stale_tmp_keeps_live_writer(tmp_path):
    """Orphaned .tmp-* staging dirs (pid dead) are swept on resume; a
    LIVE writer's staging dir — same format, our own pid — is left
    alone."""
    ckpt_dir = str(tmp_path / "c")
    net = _net()
    checkpoint.save_checkpoint(ckpt_dir, 1, net)
    dead = os.path.join(ckpt_dir, ".tmp-7-0-999999")   # no such pid
    live = os.path.join(ckpt_dir, f".tmp-8-0-{os.getpid()}")
    legacy = os.path.join(ckpt_dir, ".tmp-9-123456")   # old 2-part name
    for d in (dead, live, legacy):
        os.makedirs(d)
    step, _ = checkpoint.resume(ckpt_dir, _net())
    assert step == 1
    assert not os.path.exists(dead)
    assert not os.path.exists(legacy)
    assert os.path.exists(live)
    os.rmdir(live)
    checkpoint.save_checkpoint(ckpt_dir, 2, net)
    os.makedirs(dead)
    checkpoint.prune_checkpoints(ckpt_dir, keep=1)     # sweeps too
    assert not os.path.exists(dead)


def test_resume_falls_back_on_torn_manifest(tmp_path):
    """A checkpoint whose manifest is corrupt (torn at the byte level,
    PAST the atomic-rename completeness check) must not kill the job:
    resume warns and falls back to the previous complete checkpoint."""
    ckpt_dir = str(tmp_path / "c")
    net = _net()
    tr = _trainer(net)
    _step(net, tr, 0)
    checkpoint.save_checkpoint(ckpt_dir, 1, net, tr)
    _step(net, tr, 1)
    checkpoint.save_checkpoint(ckpt_dir, 2, net, tr)
    with open(os.path.join(ckpt_dir, "ckpt-2", "manifest.json"), "w") as f:
        f.write('{"step": 2, "has_tr')  # truncated mid-key
    net2 = _net()
    with pytest.warns(UserWarning, match="torn"):
        step, _ = checkpoint.resume(ckpt_dir, net2)
    assert step == 1


def test_resume_falls_back_on_missing_member(tmp_path):
    ckpt_dir = str(tmp_path / "c")
    net = _net()
    checkpoint.save_checkpoint(ckpt_dir, 1, net)
    checkpoint.save_checkpoint(ckpt_dir, 2, net)
    os.remove(os.path.join(ckpt_dir, "ckpt-2", "model.params"))
    with pytest.warns(UserWarning, match="torn"):
        step, _ = checkpoint.resume(ckpt_dir, _net())
    assert step == 1


def test_resume_every_checkpoint_torn_raises(tmp_path):
    from mxnet_tpu.base import MXNetError

    ckpt_dir = str(tmp_path / "c")
    net = _net()
    checkpoint.save_checkpoint(ckpt_dir, 1, net)
    os.remove(os.path.join(ckpt_dir, "ckpt-1", "model.params"))
    with pytest.warns(UserWarning, match="torn"):
        with pytest.raises(MXNetError, match="torn"):
            checkpoint.resume(ckpt_dir, _net())


def test_resume_contract_error_is_not_swallowed(tmp_path):
    """A COMPLETE checkpoint that can't satisfy the caller (saved without
    trainer state, resumed with a trainer) is a caller bug, not a torn
    checkpoint — it must raise, not silently fall back."""
    from mxnet_tpu.base import MXNetError

    ckpt_dir = str(tmp_path / "c")
    net = _net()
    checkpoint.save_checkpoint(ckpt_dir, 1, net)   # no trainer state
    net2 = _net()
    tr2 = _trainer(net2)
    tr2._init_kvstore()
    with pytest.raises(MXNetError, match="trainer"):
        checkpoint.resume(ckpt_dir, net2, tr2)


def test_sharded_checkpoint_roundtrip_preserves_sharding(tmp_path):
    """sharded=True routes weights through orbax/tensorstore: values AND
    dp/tp shardings survive resume without a host-side gather."""
    import jax

    from mxnet_tpu import parallel

    mesh = parallel.make_mesh({"dp": 4, "tp": 2})
    with parallel.mesh_scope(mesh):
        mx.random.seed(9)
        net = _net()
        x = nd.ones((2, 6))
        net(x)
        parallel.replicate_block_params(net)
        parallel.shard_param(net.weight, ("tp", None))
        want = {k: p.data().asnumpy()
                for k, p in net._collect_params_with_prefix().items()}

        d = str(tmp_path / "sharded")
        checkpoint.save_checkpoint(d, 7, net, sharded=True)

        mx.random.seed(10)  # different init: resume must overwrite it
        net2 = _net()
        net2(x)
        parallel.replicate_block_params(net2)
        parallel.shard_param(net2.weight, ("tp", None))
        step, _ = checkpoint.resume(d, net2)
        assert step == 7
        for k, p in net2._collect_params_with_prefix().items():
            np.testing.assert_allclose(p.data().asnumpy(), want[k],
                                       rtol=1e-6)
        sh = net2.weight.data()._data.sharding
        assert "tp" in str(getattr(sh, "spec", "")), sh
