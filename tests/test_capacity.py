"""Capacity observability (r20): duty-cycle ledgers, λ/μ/ρ headroom
estimators, the saturation watch, and the serving integration.

Four layers of proof:

* **Pure units** — the EWMA / rate-estimator / interval-ledger pieces
  and the queue-theory functions (``service_rate`` via the operational
  utilization law, ``queue_metrics``, ``duty_cycle``) driven with
  synthetic clocks: no serving stack, no real time.
* **Watch semantics** — the saturation watch is edge-triggered with
  hysteresis: one event per crossing, re-armed only after ρ falls
  below threshold × 0.8, gated on a minimum completion count.
* **Cost contract** — disabled, every hook is one module-global
  boolean: a poisoned lock proves nothing is acquired, and 10k no-op
  hook calls stay under the same bound the other telemetry tiers hold.
* **Serving end-to-end** — on a dp2 CPU-mesh generative server, an
  injected burst drives ρ past threshold and the ``saturation`` JSONL
  record lands in the stream BEFORE the first queue-wait breach (the
  leading-indicator claim), the r12 flight recorder dumps with
  ``reason="saturation"``, ``/healthz`` reports the degraded-but-alive
  ``saturated`` status at HTTP 200, and the scrape carries the
  utilization/ρ/headroom gauge families.
"""
import json
import time
import urllib.request

import numpy as np
import pytest

from mxnet_tpu import serving, telemetry
from mxnet_tpu.serving import ServerConfig
from mxnet_tpu.telemetry import capacity, tracing
from mxnet_tpu.telemetry.sinks import ListSink


def _capacity_off():
    capacity.disable()
    capacity.reset()


# --- pure units: estimators --------------------------------------------------

def test_ewma_first_sample_seeds():
    e = capacity.EWMA(alpha=0.5)
    assert e.value is None
    assert e.update(10.0) == 10.0
    assert e.update(0.0) == 5.0
    assert e.update(5.0) == 5.0


def test_rate_estimator_steady_stream():
    r = capacity.RateEstimator(alpha=0.2)
    assert r.rate is None                  # one event is not a rate
    for i in range(20):
        r.observe(i * 0.1)                 # 10 events/sec
    assert r.count == 20
    assert r.rate == pytest.approx(10.0, rel=1e-6)
    # rate_at inside the smoothed gap: unchanged
    assert r.rate_at(1.95) == pytest.approx(10.0, rel=1e-6)


def test_rate_estimator_open_gap_decays():
    r = capacity.RateEstimator(alpha=0.2)
    for i in range(20):
        r.observe(i * 0.1)
    # a 2 s silence after a 0.1 s cadence: the open gap bounds the
    # estimate down — a stopped stream must read as a falling rate
    decayed = r.rate_at(1.9 + 2.0)
    assert decayed < 10.0 / 2
    # and longer silence decays further (monotone in the open gap)
    assert r.rate_at(1.9 + 8.0) < decayed


def test_event_window_rate_same_timescale_as_utilization():
    w = capacity.EventWindow(window_s=10.0)
    assert w.rate(5.0) is None
    for i in range(100):
        w.observe(1000.0 + i * 0.01)       # 100/s for 1 s
    # ramp-up span: a 1 s-old stream reports its 1 s truth
    assert w.rate(1001.0) == pytest.approx(100.0, rel=0.02)
    # 4 s later the same 100 events dilute over the 5 s observed span
    assert w.rate(1005.0) == pytest.approx(20.0, rel=0.02)
    # gone quiet: zero, not a frozen estimate
    assert w.rate(1020.0) == 0.0
    assert w.count == 100


def test_interval_ledger_window_and_rampup():
    led = capacity.IntervalLedger(window_s=10.0)
    assert led.utilization(100.0) == 0.0   # empty: no divide-by-zero
    # 1 s-old ledger, 0.5 s busy: ramp-up denominator reports 50%,
    # not 5% of an empty 10 s window
    led.add(100.0, 100.5)
    assert led.utilization(101.0) == pytest.approx(0.5)
    # intervals behind the window stop counting
    assert led.utilization(120.0) == pytest.approx(0.0, abs=1e-9)
    # clamp: overlapping double-adds cannot exceed 1.0
    led.add(200.0, 201.0)
    led.add(200.0, 201.0)
    assert led.utilization(201.0) <= 1.0


def test_interval_ledger_ignores_empty_intervals():
    led = capacity.IntervalLedger(window_s=10.0)
    led.add(5.0, 5.0)
    led.add(6.0, 4.0)
    assert led.utilization(10.0) == 0.0


# --- pure units: queue theory ------------------------------------------------

def test_service_rate_utilization_law():
    # X = 50/s at 50% busy -> the replica would do 100/s flat out
    assert capacity.service_rate(50.0, 0.5) == pytest.approx(100.0)
    # fully busy: mu == X
    assert capacity.service_rate(80.0, 1.0) == pytest.approx(80.0)
    # below the busy floor the denominator is noise, not a divisor
    assert capacity.service_rate(50.0, 0.001) is None
    assert capacity.service_rate(None, 0.5) is None
    assert capacity.service_rate(0.0, 0.5) is None


def test_queue_metrics_rho_and_headroom():
    rho, headroom = capacity.queue_metrics(50.0, 100.0)
    assert rho == pytest.approx(0.5)
    assert headroom == pytest.approx(50.0)
    # overload clamps headroom at zero, rho goes past 1
    rho, headroom = capacity.queue_metrics(120.0, 100.0)
    assert rho == pytest.approx(1.2) and headroom == 0.0
    assert capacity.queue_metrics(None, 100.0) == (None, None)
    assert capacity.queue_metrics(50.0, 0.0) == (None, None)


def test_duty_cycle_clamps_and_survives_garbage():
    assert capacity.duty_cycle(8.0, 10.0) == pytest.approx(0.8)
    assert capacity.duty_cycle(12.0, 10.0) == 1.0
    assert capacity.duty_cycle(-1.0, 10.0) == 0.0
    assert capacity.duty_cycle(5.0, 0.0) == 0.0
    assert capacity.duty_cycle(None, None) == 0.0
    assert capacity.duty_cycle("x", "y") == 0.0


# --- watch semantics (synthetic clock) ---------------------------------------

def _drive_steady(index, t0, n=100, period=0.01, busy=0.5):
    """n arrivals/completions at 1/period rps with the decode lane
    busy the given fraction of each period."""
    for i in range(n):
        now = t0 + i * period
        capacity.note_arrival(index, t=now)
        capacity.note_completion(index, t=now + period * 0.4)
        capacity.note_tick(index, 4, 8, now, now + period * busy)


def test_saturation_fires_once_and_rearms(monkeypatch):
    capacity.enable(rho_threshold=0.85, min_completions=8)
    fired = []
    monkeypatch.setattr(capacity, "_emit_saturation", fired.append)
    try:
        _drive_steady(0, 1000.0)           # rho ~= 0.5: no event
        assert fired == []
        assert capacity.saturated() is False
        # burst: 400 rps arrivals against ~200 rps mu
        t = 1001.0
        for i in range(200):
            capacity.note_arrival(0, t=t + i * 0.0025)
            if i % 2 == 0:
                now = t + i * 0.0025
                capacity.note_completion(0, t=now + 0.004)
                capacity.note_tick(0, 8, 8, now, now + 0.0049)
        assert len(fired) == 1             # edge-triggered: ONE event
        evt = fired[0]
        assert evt["record"] == "saturation"
        assert evt["rho"] >= 0.85
        assert evt["replica"] == 0
        assert evt["headroom_rps"] == 0.0 or evt["headroom_rps"] >= 0
        assert capacity.saturated(0) is True
        # drain: rate falls far below threshold * 0.8 -> re-arms
        _drive_steady(0, 1002.0, n=300, period=0.05, busy=0.1)
        assert capacity.saturated(0) is False
        # second crossing fires a second event
        t = 1020.0
        for i in range(200):
            capacity.note_arrival(0, t=t + i * 0.0025)
            if i % 2 == 0:
                now = t + i * 0.0025
                capacity.note_completion(0, t=now + 0.004)
                capacity.note_tick(0, 8, 8, now, now + 0.0049)
        assert len(fired) == 2
    finally:
        _capacity_off()


def test_saturation_gated_on_min_completions(monkeypatch):
    capacity.enable(rho_threshold=0.5, min_completions=50)
    fired = []
    monkeypatch.setattr(capacity, "_emit_saturation", fired.append)
    try:
        _drive_steady(0, 1000.0, n=40, busy=0.9)   # rho ~0.9 > 0.5 ...
        assert fired == []                 # ... but only 40 completions
    finally:
        _capacity_off()


def test_snapshot_view_fields():
    capacity.enable()
    try:
        _drive_steady(3, 1000.0, n=200)
        capacity.note_kv(3, 60, 100, fragmentation=0.25)
        capacity.note_kv(3, 50, 100, fragmentation=0.35)
        capacity.note_spec(3, 40, 25)
        snap = capacity.snapshot(3, now=1001.99)
        assert snap["replica"] == 3
        assert 0.3 < snap["utilization"] < 0.7
        assert snap["occupancy"] == pytest.approx(0.5)
        assert snap["slot_capacity"] == 8
        assert snap["spec_efficiency"] == pytest.approx(25 / 40)
        assert snap["kv_free_frac"] == pytest.approx(0.5)
        assert snap["kv_fragmentation_trend"] > 0   # fragmenting
        assert snap["arrival_rate_rps"] == pytest.approx(100.0, rel=0.05)
        assert snap["rho"] == pytest.approx(0.5, rel=0.15)
        assert snap["predicted_max_rate_rps"] == \
            snap["service_rate_rps"]
        assert snap["headroom_rps"] > 0
        # the all-replica form keys by index
        assert set(capacity.snapshot(now=1001.99)) == {3}
        # utilization query matches the view
        assert capacity.utilization(3, now=1001.99) == \
            pytest.approx(snap["utilization"], abs=1e-6)
    finally:
        _capacity_off()


def test_telemetry_enable_kwarg_arms_capacity():
    try:
        telemetry.enable(memory=False, cost=False, capacity=True)
        assert capacity.is_enabled()
    finally:
        telemetry.disable()
        telemetry.reset()
    assert not capacity.is_enabled()


# --- cost contract: the disabled path ----------------------------------------

class _PoisonLock:
    def __enter__(self):
        raise AssertionError("disabled capacity hook acquired a lock")

    def __exit__(self, *a):
        return False


def test_disabled_hooks_never_lock_or_record(monkeypatch):
    _capacity_off()
    monkeypatch.setattr(capacity, "_lock", _PoisonLock())
    capacity.note_arrival(0)
    capacity.note_completion(0, t=1.0)
    capacity.note_tick(0, 4, 8, 0.0, 1.0)
    capacity.note_spec(0, 4, 2)
    capacity.note_kv(0, 5, 10)
    capacity.lane_busy(0, "prefill", 0.0, 1.0)
    assert capacity.utilization(0) == 0.0
    assert capacity.saturated() is False
    assert capacity.snapshot(0) is None
    assert capacity.snapshot() == {}


def test_disabled_overhead_bounded():
    _capacity_off()
    t0 = time.perf_counter()
    for i in range(10_000):
        capacity.note_arrival(0, t=float(i))
        capacity.note_completion(0, t=float(i))
        capacity.note_tick(0, 4, 8, float(i), float(i) + 0.5)
        capacity.lane_busy(0, "prefill", float(i), float(i) + 0.1)
    dt = time.perf_counter() - t0
    # 40k disabled hook crossings; the bound matches the other tiers'
    # disabled-path guards (one boolean test per call)
    assert dt < 0.5, f"disabled capacity hooks cost {dt:.3f}s per 40k"


# --- serving end-to-end: burst -> saturation precedes the wait breach --------

def _tiny():
    from mxnet_tpu.models.llama import llama_tiny

    net = llama_tiny()
    net.initialize()
    return net


def test_dp2_burst_saturation_precedes_queue_wait_breach(
        tmp_path, monkeypatch):
    """The leading-indicator claim, end to end: under an injected
    burst on a dp2 CPU-mesh server the ``saturation`` record enters
    the JSONL stream BEFORE any request record whose queue wait
    breached, the flight recorder dumps with ``reason="saturation"``,
    ``/healthz`` stays HTTP 200 with status ``saturated``, and the
    scrape exposes the capacity gauge families."""
    import jax
    from jax.sharding import Mesh

    if len(jax.devices()) < 2:
        pytest.skip("needs 2 devices (dp2)")
    breach_ms = 50.0
    dump_path = tmp_path / "flight.json"
    monkeypatch.setenv("MXNET_TRACE_DUMP", str(dump_path))
    net = _tiny()
    rs = np.random.RandomState(7)
    mesh = Mesh(np.array(jax.devices()[:2]), ("dp",))
    cfg = ServerConfig(max_batch=2, max_length=64, min_length=8,
                       num_slots=2, summary_every=1 << 30,
                       http_port=0)
    telemetry.enable(memory=False, cost=False, trace=True)
    sink = ListSink()
    telemetry.add_sink(sink)
    capacity.enable(rho_threshold=0.85, min_completions=6)
    try:
        srv = serving.GenerativeServer(net, cfg, mesh=mesh)
        with srv:
            url = srv.metrics_url
            # warm trickle: enough completions per replica to trust mu,
            # spaced so the duty cycle stays well under the threshold
            for _ in range(14):
                srv.generate(rs.randint(1, 250, size=6),
                             max_new_tokens=3)
                time.sleep(0.01)
            assert capacity.saturated() is False
            # burst: far more than 2 replicas x 2 slots can drain
            futs = [srv.submit(rs.randint(1, 250, size=6),
                               max_new_tokens=6) for _ in range(24)]
            for f in futs:
                f.result(300)
            # the watch re-arms as the drain pulls rho back down, so
            # health is checked with the flag deterministically held:
            # a live crossing is timing, the PLUMBING is the claim here
            with capacity._lock:
                capacity._replica(0).saturated = True
            health = json.loads(
                urllib.request.urlopen(url + "/healthz").read())
            code = urllib.request.urlopen(url + "/healthz").status
            mtxt = urllib.request.urlopen(url + "/metrics").read() \
                .decode()
            stats = srv.stats()
            counters = dict(telemetry.counters())
    finally:
        telemetry.disable()
        telemetry.reset()
        tracing.clear()
        _capacity_off()

    # -- the stream ordering: saturation precedes the wait breach ------------
    sat_idx = [i for i, r in enumerate(sink.records)
               if r.get("record") == "saturation"]
    assert sat_idx, "no saturation record under a 24-deep burst"
    breach_idx = [i for i, r in enumerate(sink.records)
                  if r.get("record") == "serving.request"
                  and (r.get("queue_wait_ms") or 0.0) > breach_ms]
    assert breach_idx, "burst produced no queue-wait breach to lead"
    assert sat_idx[0] < breach_idx[0], (
        "saturation must be a LEADING indicator: record index %d vs "
        "first breach at %d" % (sat_idx[0], breach_idx[0]))
    sat = sink.records[sat_idx[0]]
    assert sat["rho"] >= 0.85
    assert sat["replica"] in (0, 1)
    assert sat["service_rate_rps"] > 0
    assert counters.get("capacity.saturation", 0) >= 1

    # -- the flight recorder armed on the crossing ---------------------------
    assert dump_path.exists()
    report = json.loads(dump_path.read_text())
    assert report["record"] == "flight_recorder"
    assert report["reason"] == "saturation"
    assert report["context"]["rho"] >= 0.85

    # -- degraded-but-alive health + gauges ----------------------------------
    assert code == 200
    assert health["status"] == "saturated"
    sat_reps = [r for r in health["replicas"] if r.get("saturated")]
    assert sat_reps and all("rho" in r and "headroom_rps" in r
                            for r in sat_reps)
    assert "mxt_serving_utilization" in mtxt
    assert "mxt_serving_rho" in mtxt
    assert "mxt_serving_headroom_rps" in mtxt
    assert "mxt_serving_kv_free_frac" in mtxt

    # -- stats carries the per-replica capacity views ------------------------
    caps = stats["capacity"]
    assert len(caps) == 2
    assert {c["replica"] for c in caps} == {0, 1}
    assert sum(c["saturation_events"] for c in caps) >= 1
