"""Loopback training worker for the preemption/elastic/chaos suites.

NOT a test module — tests launch this under ``tools/launch.py`` (and
``tools/chaos.py``) with the env contract below.  One worker serves all
three suites because the training loop IS the contract under test: an
elastic, preemption-safe loop that any rank count can resume.

  REPO_ROOT     repo checkout (sys.path bootstrap)
  CKPT_DIR      checkpoint directory shared across (re)launches
  TOTAL_STEPS   train until this global step
  OUT_FILE      prefix; final params land at OUT_FILE<rank>.npy
  LOSS_FILE     rank 0 appends "step loss" per step (elastic oracle)
  CKPT_MODE     "async" (default) or "sync" rank-0 checkpoints
  STEP_SLEEP    seconds to sleep per step (widens the chaos window)
  MARKER_FILE / MARKER_AFTER_STEP
                rank 0 touches MARKER_FILE after completing that step
                (lets a test synchronize its signal with progress)
  FLEET_JSONL   prefix; enables telemetry + the fleet layer, each rank
                logging to FLEET_JSONL<rank>.jsonl (append across
                relaunches); FLEET_STRIDE sets the exchange stride
  SLOW_RANK / SLOW_SLEEP
                test hook: that rank sleeps SLOW_SLEEP seconds inside
                every step's compute phase — the injected straggler the
                fleet watchdog must name

The loop demonstrates the full robustness contract:
  * data comes from ``mxnet_tpu.elastic`` — a pure function of
    (seed, step, world, rank), so any world size replays the same
    global batch sequence;
  * rank 0 checkpoints every step (async by default);
  * every rank polls ``drain_consensus()`` after each step — SIGTERM on
    ANY subset of ranks drains the whole group at one step boundary,
    rank 0 cuts the final checkpoint, everyone exits PREEMPTED_EXIT_CODE.
"""
import os
import sys
import time

sys.path.insert(0, os.environ["REPO_ROOT"])
os.environ.pop("XLA_FLAGS", None)
import jax

jax.config.update("jax_platforms", os.environ.get("JAX_PLATFORMS", "cpu"))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd, checkpoint, elastic, gluon, nd, parallel
from mxnet_tpu import telemetry
from mxnet_tpu.gluon import trainer as trainer_mod

trainer_mod.install_preemption_handler()
parallel.initialize()
rank, world = jax.process_index(), jax.process_count()

fleet_prefix = os.environ.get("FLEET_JSONL")
if fleet_prefix:
    jsonl = f"{fleet_prefix}{rank}.jsonl"
    # a SIGKILL mid-write can leave a half line at the tail; drop it
    # before appending or the relaunch would splice two records together
    if os.path.exists(jsonl):
        with open(jsonl, "rb") as f:
            data = f.read()
        if data and not data.endswith(b"\n"):
            keep = data[:data.rfind(b"\n") + 1] if b"\n" in data else b""
            with open(jsonl, "wb") as f:
                f.write(keep)
    telemetry.enable(jsonl_path=jsonl, append=True)
    telemetry.fleet.enable(stride=int(os.environ.get("FLEET_STRIDE", "8")))
slow_rank = int(os.environ.get("SLOW_RANK", "-1"))
slow_sleep = float(os.environ.get("SLOW_SLEEP", "0"))

mx.random.seed(42)
net = gluon.nn.Dense(3, use_bias=True)
net.initialize(mx.init.Xavier())
net(nd.ones((1, 5)))
trainer = gluon.Trainer(net.collect_params(), "sgd",
                        {"learning_rate": 0.1, "momentum": 0.9},
                        kvstore="dist_tpu_sync")

ckpt_dir = os.environ["CKPT_DIR"]
total = int(os.environ["TOTAL_STEPS"])
loss_file = os.environ.get("LOSS_FILE")
step_sleep = float(os.environ.get("STEP_SLEEP", "0"))
ckpt_async = os.environ.get("CKPT_MODE", "async") != "sync"
marker = os.environ.get("MARKER_FILE")
marker_step = int(os.environ.get("MARKER_AFTER_STEP", "-1"))

start, _ = checkpoint.resume(ckpt_dir, net, trainer)
if start:
    print(f"rank {rank}: resumed from step {start} (world={world})",
          flush=True)

DATA = np.random.RandomState(0).randn(64, 5).astype(np.float32)
BATCH = 8

for step in range(start, total):
    telemetry.step_begin()
    idx = elastic.shard_for_step(len(DATA), BATCH, step, world, rank,
                                 seed=5)
    x = nd.array(DATA[idx])
    with autograd.record():
        loss = (net(x) ** 2).sum()
    loss.backward()
    if rank == slow_rank and slow_sleep:
        time.sleep(slow_sleep)  # injected compute straggle (test hook)
    trainer.step(BATCH)
    t_bar = time.perf_counter()
    gloss = parallel.process_sum_hostvec(
        np.asarray([float(loss.asnumpy())], dtype=np.float64))[0]
    # the gloss psum is this loop's blocking aggregation barrier: count
    # its wall time as allreduce wait so the fleet exchange can split
    # compute skew (the straggler) from wait skew (its victims)
    telemetry.count("trainer.allreduce_wait_ms",
                    (time.perf_counter() - t_bar) * 1e3)
    telemetry.step_end(examples=BATCH, loss=float(gloss),
                       global_step=step)
    if rank == 0:
        if loss_file:
            with open(loss_file, "a") as f:
                f.write(f"{step} {gloss:.9e}\n")
        if ckpt_async:
            checkpoint.save_checkpoint_async(ckpt_dir, step + 1, net,
                                             trainer)
        else:
            checkpoint.save_checkpoint(ckpt_dir, step + 1, net, trainer)
        if marker and step == marker_step:
            with open(marker, "w") as f:
                f.write(str(step))
    if step_sleep:
        time.sleep(step_sleep)
    if trainer_mod.drain_consensus():
        print(f"rank {rank}: draining at step {step + 1}", flush=True)
        if rank == 0:
            checkpoint.drain_checkpoint_and_exit(ckpt_dir, step + 1, net,
                                                 trainer)
        sys.exit(trainer_mod.PREEMPTED_EXIT_CODE)

if rank == 0:
    checkpoint.wait_async()
np.save(os.environ["OUT_FILE"] + str(rank) + ".npy",
        np.concatenate([net.weight.data().asnumpy().ravel(),
                        net.bias.data().asnumpy().ravel()]))
print(f"rank {rank}: done at step {total}", flush=True)
