"""The streaming data plane (r14): sharded readers, packing, prefetch,
and the elastic 2→1→2 contract through the REAL loader.

Unit tests pin the reader's determinism/sharding algebra, the packer's
mask/label semantics + efficiency, idx-file tolerance, the prefetcher's
wait accounting, and the dataloader teardown regression; the
integration test extends ``tests/test_elastic.py``'s resize pattern to
a pipeline fed from actual ``.rec`` shards.
"""
import os
import signal
import socket
import subprocess
import sys

import numpy as np
import pytest

from mxnet_tpu import data, elastic, recordio
from mxnet_tpu.base import MXNetError

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
WORKER = os.path.join(REPO, "tests", "_data_plane_worker.py")


def _write_shards(d, n_shards=2, per_shard=16, feat=5):
    """Deterministic float32 feature shards (sample i = all-i vector)."""
    for s in range(n_shards):
        rec = recordio.MXIndexedRecordIO(
            os.path.join(d, f"part{s}.idx"),
            os.path.join(d, f"part{s}.rec"), "w")
        for i in range(per_shard):
            v = np.full(feat, s * per_shard + i, dtype=np.float32)
            rec.write_idx(i, v.tobytes())
        rec.close()
    return d


def _decode(b):
    return np.frombuffer(b, dtype=np.float32)


# --- reader ------------------------------------------------------------------

def test_reader_global_table_and_random_access(tmp_path):
    d = _write_shards(str(tmp_path))
    with data.ShardedRecordReader(d, batch_size=8, seed=3) as r:
        assert len(r) == 32 and r.num_shards == 2
        # position i maps to the all-i record, across the shard boundary
        for i in (0, 15, 16, 31):
            np.testing.assert_array_equal(_decode(r.read(i)),
                                          np.full(5, i, np.float32))


def test_reader_rank_slices_partition_the_global_draw(tmp_path):
    d = _write_shards(str(tmp_path))
    r = data.ShardedRecordReader(d, batch_size=8, seed=3)
    for step in (0, 1, 9):
        full = r.global_indices_for_step(step)
        for world in (1, 2, 4):
            parts = [r.batch_indices_for_step(step, world, rk)
                     for rk in range(world)]
            np.testing.assert_array_equal(np.concatenate(parts), full)
    # the draw matches elastic directly — the loader adds nothing on top
    np.testing.assert_array_equal(
        r.global_indices_for_step(4),
        elastic.global_batch_indices(32, 8, 4, seed=3))


def test_reader_missing_idx_raises(tmp_path):
    rec = os.path.join(str(tmp_path), "x.rec")
    with open(rec, "wb"):
        pass
    with pytest.raises(MXNetError, match="idx"):
        data.ShardedRecordReader(rec, batch_size=4)


# --- recordio idx tolerance (satellite) -------------------------------------

def test_indexed_recordio_tolerates_blank_idx_lines(tmp_path):
    d = _write_shards(str(tmp_path), n_shards=1)
    idx = os.path.join(d, "part0.idx")
    with open(idx, "a") as f:
        f.write("\n  \n\n")  # trailing newline + blank lines
    r = recordio.MXIndexedRecordIO(idx, os.path.join(d, "part0.rec"), "r")
    assert len(r.keys) == 16
    np.testing.assert_array_equal(_decode(r.read_idx(7)),
                                  np.full(5, 7, np.float32))
    r.close()
    # the sharded reader tolerates the same file
    with data.ShardedRecordReader(d, batch_size=4) as sr:
        assert len(sr) == 16


def test_indexed_recordio_corrupt_idx_line_raises_named_error(tmp_path):
    d = _write_shards(str(tmp_path), n_shards=1)
    idx = os.path.join(d, "part0.idx")
    with open(idx, "a") as f:
        f.write("not-a-key\n")
    with pytest.raises(MXNetError, match="corrupt index line"):
        recordio.MXIndexedRecordIO(idx, os.path.join(d, "part0.rec"), "r")
    with pytest.raises(MXNetError, match="corrupt index line"):
        data.ShardedRecordReader(d, batch_size=4)


# --- sequence packing --------------------------------------------------------

def test_packer_mask_label_semantics():
    batch, stats = data.pack_documents(
        [np.arange(1, 6), np.arange(1, 10), np.arange(1, 4)],
        batch_size=2, seq_len=8)
    # row 0: [1..5][1..3], row 1: [1..8 truncated from 1..9]
    np.testing.assert_array_equal(batch.tokens[0],
                                  [1, 2, 3, 4, 5, 1, 2, 3])
    np.testing.assert_array_equal(batch.segment_ids[0],
                                  [1, 1, 1, 1, 1, 2, 2, 2])
    # labels: next token WITHIN a segment; last position of each
    # segment masked (no cross-document prediction)
    np.testing.assert_array_equal(batch.labels[0],
                                  [2, 3, 4, 5, 0, 2, 3, 0])
    np.testing.assert_array_equal(batch.loss_mask[0],
                                  [1, 1, 1, 1, 0, 1, 1, 0])
    assert stats.docs_packed == 3
    assert stats.tokens_dropped == 1  # 9-doc truncated by one


def test_packer_padding_and_efficiency_accounting():
    p = data.SequencePacker(batch_size=2, seq_len=8)
    b = p.pack([np.arange(1, 7), np.arange(1, 6)])   # 6 + 5 tokens
    assert (b.segment_ids[b.tokens == 0] == 0).all()
    assert (b.loss_mask[b.segment_ids == 0] == 0).all()
    st = p.stats
    assert st.tokens_kept == 11 and st.tokens_padded == 5
    assert st.efficiency() == pytest.approx(11 / 16)


def test_packer_is_deterministic_and_rank_independent():
    """Every rank packs the same global draw identically; rank rows are
    contiguous slices whose union is the global grid — the elastic
    parity contract for the packed path."""
    rng = np.random.RandomState(7)
    docs = [np.arange(1, rng.randint(4, 60)) for _ in range(40)]
    b1, _ = data.pack_documents(docs, batch_size=8, seq_len=64)
    b2, _ = data.pack_documents(docs, batch_size=8, seq_len=64)
    np.testing.assert_array_equal(b1.tokens, b2.tokens)
    np.testing.assert_array_equal(b1.segment_ids, b2.segment_ids)
    rows_w2 = [b1.rows(elastic.shard_rows(8, 2, rk)) for rk in (0, 1)]
    np.testing.assert_array_equal(
        np.concatenate([r.tokens for r in rows_w2]), b1.tokens)


def test_packer_efficiency_on_mixed_corpus_meets_bar():
    """≥85% token efficiency on a mixed-length synthetic corpus — the
    r14 acceptance bar the bench lane re-proves end to end."""
    rng = np.random.RandomState(0)
    lens = rng.randint(8, 200, size=400)
    docs = [rng.randint(1, 1000, size=n) for n in lens]
    p = data.SequencePacker(batch_size=8, seq_len=256)
    i = 0
    while i < len(docs):
        p.pack(docs[i:i + 64])
        i += 64
    assert p.stats.efficiency() >= 0.85, p.stats.as_dict()


# --- prefetcher --------------------------------------------------------------

def test_prefetcher_orders_batches_and_accounts_wait():
    from mxnet_tpu import telemetry

    class _Sink:
        def __init__(self):
            self.records = []

        def emit(self, record):
            self.records.append(record)

        def close(self):
            pass

    telemetry.enable(memory=False, cost=False)
    sink = _Sink()
    telemetry.add_sink(sink)
    try:
        batches = [np.full((4, 3), i, np.float32) for i in range(5)]
        with data.DevicePrefetcher(iter(batches), depth=2) as p:
            telemetry.step_begin()
            got = [p.get(timeout=30) for _ in range(5)]
            rec = telemetry.step_end()
            with pytest.raises(StopIteration):
                p.get(timeout=30)
        for i, g in enumerate(got):
            np.testing.assert_array_equal(g.asnumpy(), batches[i])
        # the consumer wait rides the JSONL record as data_wait_ms
        assert "data_wait_ms" in rec
        assert rec["data_wait_ms"] >= 0.0
    finally:
        telemetry.disable()


def test_prefetcher_propagates_source_errors():
    def bad_source():
        yield np.zeros((2, 2), np.float32)
        raise RuntimeError("decode exploded")

    with data.DevicePrefetcher(bad_source(), depth=2) as p:
        p.get(timeout=30)
        with pytest.raises(RuntimeError, match="decode exploded"):
            p.get(timeout=30)


# --- streaming loader --------------------------------------------------------

def test_streaming_loader_matches_direct_reads(tmp_path):
    d = _write_shards(str(tmp_path))
    r = data.ShardedRecordReader(d, batch_size=8, seed=3)
    expect = [np.stack([_decode(r.read(i))
                        for i in r.batch_indices_for_step(s, 2, 0)])
              for s in range(4)]
    with data.StreamingLoader(r, transform=_decode, num_workers=2,
                              num_steps=4, world_size=2,
                              rank=0) as loader:
        got = [b.asnumpy() for b in loader]
    assert len(got) == 4
    for e, g in zip(expect, got):
        np.testing.assert_array_equal(e, g)


def test_streaming_loader_resume_is_start_step(tmp_path):
    """Resume = construct at the checkpointed step: a loader started at
    step 2 replays exactly the tail of a from-scratch run."""
    d = _write_shards(str(tmp_path))
    r1 = data.ShardedRecordReader(d, batch_size=8, seed=3)
    with data.StreamingLoader(r1, transform=_decode, num_workers=2,
                              num_steps=5, world_size=1,
                              rank=0) as full:
        all_b = [b.asnumpy() for b in full]
    r2 = data.ShardedRecordReader(d, batch_size=8, seed=3)
    with data.StreamingLoader(r2, transform=_decode, num_workers=2,
                              start_step=2, num_steps=3, world_size=1,
                              rank=0) as tail:
        tail_b = [b.asnumpy() for b in tail]
    for e, g in zip(all_b[2:], tail_b):
        np.testing.assert_array_equal(e, g)


def test_streaming_loader_packed_mode_elastic_rows(tmp_path):
    """Packed mode: both ranks of a 2-world pack the identical global
    grid; their row slices concatenate back to the world-1 batch."""
    d = _write_shards(str(tmp_path))

    def tok(b):
        v = _decode(b)
        return (v[:3].astype(np.int32) % 7) + 1

    def run(world, rank):
        r = data.ShardedRecordReader(d, batch_size=8, seed=3)
        packer = data.SequencePacker(batch_size=2, seq_len=16)
        with data.StreamingLoader(r, packer=packer, tokenize=tok,
                                  num_workers=0, num_steps=2,
                                  world_size=world, rank=rank) as ld:
            return [(b.tokens.asnumpy(), b.segment_ids.asnumpy())
                    for b in ld]

    w1 = run(1, 0)
    r0, r1 = run(2, 0), run(2, 1)
    for s in range(2):
        np.testing.assert_array_equal(
            np.concatenate([r0[s][0], r1[s][0]]), w1[s][0])
        np.testing.assert_array_equal(
            np.concatenate([r0[s][1], r1[s][1]]), w1[s][1])


# --- dataloader teardown regression (satellite) ------------------------------

class _ExplodingDataset:
    """Picklable dataset that fails mid-epoch (index 9)."""

    def __getitem__(self, i):
        if i == 9:
            raise ValueError("exploding sample 9")
        return np.zeros(3, np.float32)

    def __len__(self):
        return 16


def test_dataloader_failed_epoch_tears_down_workers():
    """A failed epoch must not leave orphaned worker processes: the
    pool is closed on the exception path (and respawned on next use)."""
    from mxnet_tpu.gluon.data import DataLoader

    loader = DataLoader(_ExplodingDataset(), batch_size=2, num_workers=2,
                        worker_type="process")
    with pytest.raises(MXNetError, match="exploding sample 9"):
        list(loader)
    assert loader._pool is None  # torn down, not orphaned
    # a later epoch over a healthy dataset respawns cleanly
    loader2 = DataLoader(_SquareAfterFailure(), batch_size=2,
                         num_workers=2, worker_type="process")
    try:
        out = list(loader2)
        assert len(out) == 4
    finally:
        loader2.close()


class _SquareAfterFailure:
    def __getitem__(self, i):
        return np.float32(i) ** 2

    def __len__(self):
        return 8


def test_dataloader_break_keeps_pool_for_next_epoch():
    """GeneratorExit (break / del) is NOT a failure: the persistent
    pool survives for the next epoch (existing behavior pinned)."""
    from mxnet_tpu.gluon.data import DataLoader

    loader = DataLoader(_SquareAfterFailure(), batch_size=2,
                        num_workers=2, worker_type="process")
    try:
        it = iter(loader)
        next(it)
        del it
        assert loader._pool is not None
        assert len(list(loader)) == 4
    finally:
        loader.close()


# --- integration: elastic 2→1→2 through the real loader ---------------------

def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _launch(n, ckpt, total, out, loss, rec_dir, port, timeout=300):
    env = dict(os.environ)
    env.update(REPO_ROOT=REPO, CKPT_DIR=ckpt, TOTAL_STEPS=str(total),
               OUT_FILE=out, LOSS_FILE=loss, REC_DIR=rec_dir,
               MXT_LAUNCH_PLATFORM="cpu")
    proc = subprocess.Popen(
        [sys.executable, os.path.join(REPO, "tools", "launch.py"),
         "-n", str(n), "--coordinator", f"127.0.0.1:{port}",
         sys.executable, WORKER],
        env=env, start_new_session=True, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True)
    try:
        log, _ = proc.communicate(timeout=timeout)
    except subprocess.TimeoutExpired:
        os.killpg(os.getpgid(proc.pid), signal.SIGKILL)
        raise
    assert proc.returncode == 0, log[-3000:]
    return log


def _losses(path):
    out = {}
    with open(path) as f:
        for line in f:
            step, loss = line.split()
            out[int(step)] = float(loss)
    return [out[k] for k in sorted(out)]


@pytest.mark.skipif(sys.platform != "linux", reason="loopback group")
def test_elastic_resize_2_1_2_through_real_loader(tmp_path):
    """Acceptance: the 2→1→2 resize of tests/test_elastic.py, but with
    every batch streamed from .rec shards through the full data plane —
    per-step losses and final params equal the fixed-size oracles."""
    total = 6
    d = str(tmp_path)
    rec_dir = os.path.join(d, "rec")
    os.makedirs(rec_dir)
    _write_shards(rec_dir, n_shards=2, per_shard=32)

    seg = [("a", 2, 2), ("b", 1, 4), ("c", 2, 6)]  # (tag, world, until)
    for tag, world, until in seg:
        log = _launch(world, d + "/ck", until, f"{d}/seg_{tag}_",
                      f"{d}/loss_resized", rec_dir, _free_port())
        if tag != "a":
            assert "resumed from step" in log, log[-2000:]

    _launch(2, d + "/ck2", total, f"{d}/o2_", f"{d}/loss_w2", rec_dir,
            _free_port())
    _launch(1, d + "/ck1", total, f"{d}/o1_", f"{d}/loss_w1", rec_dir,
            _free_port())

    resized = _losses(f"{d}/loss_resized")
    for oracle_file in ("loss_w2", "loss_w1"):
        oracle = _losses(f"{d}/{oracle_file}")
        assert len(resized) == len(oracle) == total
        np.testing.assert_allclose(resized, oracle, rtol=1e-5,
                                   err_msg=oracle_file)

    final = np.load(f"{d}/seg_c_0.npy")
    np.testing.assert_allclose(final, np.load(f"{d}/o2_0.npy"), rtol=1e-5)
    np.testing.assert_allclose(final, np.load(f"{d}/o1_0.npy"), rtol=1e-5)
