"""Multi-process distributed loopback test.

Reference model (SURVEY §4): the nightly dist tests spawn scheduler +
servers + workers as local processes via ``tools/launch.py -n N --launcher
local`` and assert cross-worker consistency after push/pull rounds
(tests/nightly/dist_sync_kvstore.py:?).  TPU analog: N local CPU
processes form a ``jax.distributed`` group through the same launcher env
contract (MXT_*), run a psum over the process mesh, and every replica
must hold the identical global result.
"""
import os
import subprocess
import sys

import pytest

TOOLS = os.path.join(os.path.dirname(__file__), "..", "tools")

_WORKER = r"""
import os
import sys
sys.path.insert(0, os.environ["REPO_ROOT"])
# each process is a single-device CPU host in the group
os.environ.pop("XLA_FLAGS", None)
import jax
jax.config.update("jax_platforms", "cpu")

import mxnet_tpu as mx
from mxnet_tpu import nd, parallel

parallel.initialize()  # picks up MXT_* env from tools/launch.py
rank = jax.process_index()
n = jax.process_count()
assert n == int(os.environ["MXT_NUM_PROCESSES"])

import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

mesh = parallel.make_mesh({"dp": n})
with parallel.mesh_scope(mesh):
    # global (n, 4): each process owns one row filled with rank+1;
    # after psum over dp every replica must hold n(n+1)/2
    sharding = NamedSharding(mesh, P("dp", None))
    garr = jax.make_array_from_process_local_data(
        sharding, np.full((1, 4), float(rank + 1), np.float32))

    def summed(x):
        return jax.lax.psum(x, "dp")

    out = jax.jit(jax.shard_map(summed, mesh=mesh,
                                in_specs=P("dp", None),
                                out_specs=P("dp", None)))(garr)
    want = n * (n + 1) / 2
    got = np.asarray(out.addressable_data(0))
    assert np.allclose(got, want), (rank, got, want)

with open(os.environ["OUT_FILE"] + os.environ["MXT_PROCESS_ID"], "w") as f:
    f.write("ok")
"""


def _free_port():
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.mark.skipif(sys.platform != "linux", reason="loopback group")
def test_jax_distributed_loopback_psum(tmp_path):
    import signal

    script = tmp_path / "worker.py"
    script.write_text(_WORKER)
    out = str(tmp_path / "out")
    env = dict(os.environ)
    env["OUT_FILE"] = out
    env["MXT_LAUNCH_PLATFORM"] = "cpu"
    env["REPO_ROOT"] = os.path.join(os.path.dirname(__file__), "..")
    n = 2
    # own session so a timeout can reap launch.py AND its workers; free
    # port so concurrent runs don't collide
    proc = subprocess.Popen(
        [sys.executable, os.path.join(TOOLS, "launch.py"), "-n", str(n),
         "--coordinator", f"127.0.0.1:{_free_port()}",
         sys.executable, str(script)], env=env, start_new_session=True)
    try:
        rc = proc.wait(timeout=240)
    except subprocess.TimeoutExpired:
        os.killpg(os.getpgid(proc.pid), signal.SIGKILL)
        raise
    assert rc == 0
    for i in range(n):
        assert os.path.exists(out + str(i)), f"worker {i} did not finish"


_TRAIN_WORKER = r"""
import os
import sys
sys.path.insert(0, os.environ["REPO_ROOT"])
os.environ.pop("XLA_FLAGS", None)
import jax
jax.config.update("jax_platforms", "cpu")

import numpy as np
import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd, parallel

parallel.initialize()
rank, n = jax.process_index(), jax.process_count()

mx.random.seed(42)
net = gluon.nn.Dense(3, use_bias=True)
net.initialize(mx.init.Xavier())
net(nd.ones((1, 5)))
trainer = gluon.Trainer(net.collect_params(), "sgd",
                        {"learning_rate": 0.1, "momentum": 0.9},
                        kvstore="dist_tpu_sync")

full = np.random.RandomState(0).randn(8, 5).astype(np.float32)
shard = full[rank * 4:(rank + 1) * 4]          # disjoint per-rank data
x = nd.array(shard)
for _ in range(4):
    with autograd.record():
        loss = (net(x) ** 2).sum()             # sum-loss: step() rescales
    loss.backward()
    trainer.step(8)                            # GLOBAL batch size
assert trainer._kvstore.num_workers == n
np.save(os.environ["OUT_FILE"] + str(rank) + ".npy",
        np.concatenate([net.weight.data().asnumpy().ravel(),
                        net.bias.data().asnumpy().ravel()]))
"""


@pytest.mark.skipif(sys.platform != "linux", reason="loopback group")
def test_two_process_dist_sync_trainer_matches_single(tmp_path):
    """The dist_sync_kvstore.py analog (SURVEY §4): a full 2-process
    dist_tpu_sync Trainer run — per-rank disjoint shards, cross-host
    gradient psum — must leave BYTE-IDENTICAL params on both ranks, equal
    to a single-process run over the concatenated batch."""
    import signal

    import numpy as np

    script = tmp_path / "train_worker.py"
    script.write_text(_TRAIN_WORKER)
    out = str(tmp_path / "params")
    env = dict(os.environ)
    env["OUT_FILE"] = out
    env["MXT_LAUNCH_PLATFORM"] = "cpu"
    env["REPO_ROOT"] = os.path.join(os.path.dirname(__file__), "..")
    n = 2
    proc = subprocess.Popen(
        [sys.executable, os.path.join(TOOLS, "launch.py"), "-n", str(n),
         "--coordinator", f"127.0.0.1:{_free_port()}",
         sys.executable, str(script)], env=env, start_new_session=True)
    try:
        rc = proc.wait(timeout=240)
    except subprocess.TimeoutExpired:
        os.killpg(os.getpgid(proc.pid), signal.SIGKILL)
        raise
    assert rc == 0
    got = [np.load(out + f"{i}.npy") for i in range(n)]
    assert got[0].tobytes() == got[1].tobytes(), "ranks diverged"

    # single-process oracle over the concatenated batch
    import mxnet_tpu as mx
    from mxnet_tpu import autograd, gluon, nd

    mx.random.seed(42)
    net = gluon.nn.Dense(3, use_bias=True)
    net.initialize(mx.init.Xavier())
    net(nd.ones((1, 5)))
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1, "momentum": 0.9})
    x = nd.array(np.random.RandomState(0).randn(8, 5).astype(np.float32))
    for _ in range(4):
        with autograd.record():
            loss = (net(x) ** 2).sum()
        loss.backward()
        trainer.step(8)
    want = np.concatenate([net.weight.data().asnumpy().ravel(),
                           net.bias.data().asnumpy().ravel()])
    np.testing.assert_allclose(got[0], want, rtol=1e-5, atol=1e-6)


_SHARDED_CKPT_WORKER = r"""
import os
import sys
sys.path.insert(0, os.environ["REPO_ROOT"])
os.environ.pop("XLA_FLAGS", None)
import jax
jax.config.update("jax_platforms", "cpu")

import numpy as np
import mxnet_tpu as mx
from mxnet_tpu import checkpoint, gluon, nd, parallel

parallel.initialize()
rank, n = jax.process_index(), jax.process_count()

mesh = parallel.make_mesh({"dp": n})
with parallel.mesh_scope(mesh):
    mx.random.seed(21)
    net = gluon.nn.Dense(4)
    net.initialize(mx.init.Xavier())
    net(nd.ones((1, 6)))
    parallel.replicate_block_params(net)   # global (process-spanning)
    want = net.weight.data().asnumpy().copy()

    d = os.environ["CKPT_DIR"]
    checkpoint.save_checkpoint(d, 3, net, sharded=True)  # collective

    mx.random.seed(22)   # same-on-all-ranks re-init (replication over a
                         # process-spanning mesh requires identical host
                         # values), different from the saved weights
    net2 = gluon.nn.Dense(4)
    net2.initialize(mx.init.Xavier())
    net2(nd.ones((1, 6)))
    parallel.replicate_block_params(net2)
    step, _ = checkpoint.resume(d, net2)
    assert step == 3
    np.testing.assert_allclose(net2.weight.data().asnumpy(), want,
                               rtol=1e-6)
with open(os.environ["OUT_FILE"] + os.environ["MXT_PROCESS_ID"], "w") as f:
    f.write("ok")
"""


@pytest.mark.skipif(sys.platform != "linux", reason="loopback group")
def test_two_process_collective_sharded_checkpoint(tmp_path):
    """sharded=True in a 2-process group: orbax collective write into the
    final dir, process-0 manifest after a barrier, both ranks resume to
    identical weights."""
    import signal

    script = tmp_path / "ckpt_worker.py"
    script.write_text(_SHARDED_CKPT_WORKER)
    out = str(tmp_path / "out")
    env = dict(os.environ)
    env["OUT_FILE"] = out
    env["CKPT_DIR"] = str(tmp_path / "ckpts")
    env["MXT_LAUNCH_PLATFORM"] = "cpu"
    env["REPO_ROOT"] = os.path.join(os.path.dirname(__file__), "..")
    n = 2
    proc = subprocess.Popen(
        [sys.executable, os.path.join(TOOLS, "launch.py"), "-n", str(n),
         "--coordinator", f"127.0.0.1:{_free_port()}",
         sys.executable, str(script)], env=env, start_new_session=True)
    try:
        rc = proc.wait(timeout=240)
    except subprocess.TimeoutExpired:
        os.killpg(os.getpgid(proc.pid), signal.SIGKILL)
        raise
    assert rc == 0
    for i in range(n):
        assert os.path.exists(out + str(i)), f"rank {i} did not finish"


_SYNCBN_WORKER = r"""
import os
import sys
sys.path.insert(0, os.environ["REPO_ROOT"])
os.environ.pop("XLA_FLAGS", None)
import jax
jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp
import numpy as np
import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd, parallel

parallel.initialize()
rank, n = jax.process_index(), jax.process_count()

EPS, MOM = 1e-5, 0.9
full = np.random.RandomState(7).randn(8, 3, 2, 2).astype(np.float32)
coef = np.random.RandomState(8).randn(8, 3, 2, 2).astype(np.float32)
shard = full[rank * 4:(rank + 1) * 4]

mx.random.seed(1)
net = gluon.nn.SyncBatchNorm(in_channels=3, momentum=MOM, epsilon=EPS)
net.initialize()
# nontrivial gamma/beta so sync errors can't hide behind identities
net.gamma.set_data(nd.array([1.5, 0.5, 2.0]))
net.beta.set_data(nd.array([0.1, -0.2, 0.3]))

x = nd.array(shard)
x.attach_grad()
with autograd.record():
    y = net(x)
    loss = (y * nd.array(coef[rank * 4:(rank + 1) * 4])).sum()
loss.backward()

# independent reference: jax autodiff through GLOBAL-batch BN
def ref_loss(xg, gamma, beta):
    xf = xg.astype(jnp.float32)
    mean = jnp.mean(xf, axis=(0, 2, 3))
    var = jnp.var(xf, axis=(0, 2, 3))
    sh = (1, 3, 1, 1)
    yg = (xf - mean.reshape(sh)) * jax.lax.rsqrt(var + EPS).reshape(sh)
    yg = yg * gamma.reshape(sh) + beta.reshape(sh)
    return (yg * jnp.asarray(coef)).sum(), (yg, mean, var)

gamma = jnp.asarray([1.5, 0.5, 2.0], jnp.float32)
beta = jnp.asarray([0.1, -0.2, 0.3], jnp.float32)
(_, (y_ref, mean_ref, var_ref)), grads = jax.value_and_grad(
    ref_loss, argnums=(0, 1, 2), has_aux=True)(jnp.asarray(full), gamma, beta)
dx_ref, dgamma_ref, dbeta_ref = grads

sl = slice(rank * 4, (rank + 1) * 4)
np.testing.assert_allclose(y.asnumpy(), np.asarray(y_ref)[sl],
                           rtol=1e-5, atol=1e-5)
np.testing.assert_allclose(x.grad.asnumpy(), np.asarray(dx_ref)[sl],
                           rtol=1e-4, atol=1e-5)
# per-host running stats must equal GLOBAL-batch stats (the r2 defect)
np.testing.assert_allclose(net.running_mean.data().asnumpy(),
                           (1 - MOM) * np.asarray(mean_ref),
                           rtol=1e-5, atol=1e-6)
np.testing.assert_allclose(net.running_var.data().asnumpy(),
                           MOM * 1.0 + (1 - MOM) * np.asarray(var_ref),
                           rtol=1e-5, atol=1e-6)
# param grads: LOCAL sums; all_sum (the Trainer's hop) gives the global ones
gsum = parallel.all_sum([net.gamma.grad(), net.beta.grad()])
np.testing.assert_allclose(gsum[0].asnumpy(), np.asarray(dgamma_ref),
                           rtol=1e-4, atol=1e-5)
np.testing.assert_allclose(gsum[1].asnumpy(), np.asarray(dbeta_ref),
                           rtol=1e-4, atol=1e-5)

# hybridized multi-process SyncBatchNorm must refuse loudly, not silently
# train on per-host statistics
net.hybridize()
try:
    with autograd.record():
        net(x)
    raise SystemExit("hybridized SyncBatchNorm did not raise")
except mx.base.MXNetError:
    pass

with open(os.environ["OUT_FILE"] + os.environ["MXT_PROCESS_ID"], "w") as f:
    f.write("ok")
"""


@pytest.mark.skipif(sys.platform != "linux", reason="loopback group")
def test_two_process_sync_batch_norm(tmp_path):
    """SyncBatchNorm in a 2-process dp group: forward/backward/running
    stats must all match a global-batch reference on every rank (the
    round-2 'does not sync' defect), and hybridize must raise instead of
    silently using per-host statistics."""
    import signal

    script = tmp_path / "syncbn_worker.py"
    script.write_text(_SYNCBN_WORKER)
    out = str(tmp_path / "out")
    env = dict(os.environ)
    env["OUT_FILE"] = out
    env["MXT_LAUNCH_PLATFORM"] = "cpu"
    env["REPO_ROOT"] = os.path.join(os.path.dirname(__file__), "..")
    n = 2
    proc = subprocess.Popen(
        [sys.executable, os.path.join(TOOLS, "launch.py"), "-n", str(n),
         "--coordinator", f"127.0.0.1:{_free_port()}",
         sys.executable, str(script)], env=env, start_new_session=True)
    try:
        rc = proc.wait(timeout=240)
    except subprocess.TimeoutExpired:
        os.killpg(os.getpgid(proc.pid), signal.SIGKILL)
        raise
    assert rc == 0
    for i in range(n):
        assert os.path.exists(out + str(i)), f"rank {i} did not finish"


@pytest.mark.skipif(sys.platform != "linux", reason="loopback group")
def test_composed_multihost_topology_matches_single_process():
    """VERDICT r3 item 7 — the production v5e-32 topology (8 hosts x 4
    chips) in miniature: 2 processes x 4 virtual devices each.  GSPMD
    shards the batch over each host's local 4-device mesh; the
    cross-process gradient path rides dist_tpu_sync's process
    allreduce — BOTH in one stock ``gluon.Trainer`` step.  Ranks must
    end byte-identical AND equal to a single-process 8-device GSPMD run
    over the same global batch (the composition changes the reduction
    tree, not the math).  Harness shared with dryrun_multichip phase 5
    (tools/composed_multihost.py).  Reference composition style:
    tests/nightly/dist_sync_kvstore.py:? (scheduler+server+worker in one
    test)."""
    import numpy as np

    sys.path.insert(0, os.path.join(TOOLS))
    from composed_multihost import oracle_single_process, run_composed

    got = run_composed(4)
    assert got[0].tobytes() == got[1].tobytes(), "ranks diverged"
    want = oracle_single_process(4)
    np.testing.assert_allclose(got[0], want, rtol=1e-5, atol=1e-6)
