"""Multi-process distributed loopback test.

Reference model (SURVEY §4): the nightly dist tests spawn scheduler +
servers + workers as local processes via ``tools/launch.py -n N --launcher
local`` and assert cross-worker consistency after push/pull rounds
(tests/nightly/dist_sync_kvstore.py:?).  TPU analog: N local CPU
processes form a ``jax.distributed`` group through the same launcher env
contract (MXT_*), run a psum over the process mesh, and every replica
must hold the identical global result.
"""
import os
import subprocess
import sys

import pytest

TOOLS = os.path.join(os.path.dirname(__file__), "..", "tools")

_WORKER = r"""
import os
import sys
sys.path.insert(0, os.environ["REPO_ROOT"])
# each process is a single-device CPU host in the group
os.environ.pop("XLA_FLAGS", None)
import jax
jax.config.update("jax_platforms", "cpu")

import mxnet_tpu as mx
from mxnet_tpu import nd, parallel

parallel.initialize()  # picks up MXT_* env from tools/launch.py
rank = jax.process_index()
n = jax.process_count()
assert n == int(os.environ["MXT_NUM_PROCESSES"])

import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

mesh = parallel.make_mesh({"dp": n})
with parallel.mesh_scope(mesh):
    # global (n, 4): each process owns one row filled with rank+1;
    # after psum over dp every replica must hold n(n+1)/2
    sharding = NamedSharding(mesh, P("dp", None))
    garr = jax.make_array_from_process_local_data(
        sharding, np.full((1, 4), float(rank + 1), np.float32))

    def summed(x):
        return jax.lax.psum(x, "dp")

    out = jax.jit(jax.shard_map(summed, mesh=mesh,
                                in_specs=P("dp", None),
                                out_specs=P("dp", None)))(garr)
    want = n * (n + 1) / 2
    got = np.asarray(out.addressable_data(0))
    assert np.allclose(got, want), (rank, got, want)

with open(os.environ["OUT_FILE"] + os.environ["MXT_PROCESS_ID"], "w") as f:
    f.write("ok")
"""


def _free_port():
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.mark.skipif(sys.platform != "linux", reason="loopback group")
def test_jax_distributed_loopback_psum(tmp_path):
    import signal

    script = tmp_path / "worker.py"
    script.write_text(_WORKER)
    out = str(tmp_path / "out")
    env = dict(os.environ)
    env["OUT_FILE"] = out
    env["MXT_LAUNCH_PLATFORM"] = "cpu"
    env["REPO_ROOT"] = os.path.join(os.path.dirname(__file__), "..")
    n = 2
    # own session so a timeout can reap launch.py AND its workers; free
    # port so concurrent runs don't collide
    proc = subprocess.Popen(
        [sys.executable, os.path.join(TOOLS, "launch.py"), "-n", str(n),
         "--coordinator", f"127.0.0.1:{_free_port()}",
         sys.executable, str(script)], env=env, start_new_session=True)
    try:
        rc = proc.wait(timeout=240)
    except subprocess.TimeoutExpired:
        os.killpg(os.getpgid(proc.pid), signal.SIGKILL)
        raise
    assert rc == 0
    for i in range(n):
        assert os.path.exists(out + str(i)), f"worker {i} did not finish"
