"""Serving subsystem: bucketing math, KV-slot invariants, backpressure,
and the continuous-batching acceptance paths (multi-client bit-identity
under <=4 compiled signatures; a late generative request joining an
in-flight decode batch)."""
import os
import threading
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, serialization, serving, telemetry
from mxnet_tpu.predictor import Predictor
from mxnet_tpu.serving import (BucketPolicy, KVCacheManager, RequestQueue,
                               ServerConfig, ServerOverloadedError,
                               pad_batch, pow2_bucket)
from mxnet_tpu.serving.protocol import Request, ServerClosedError
from mxnet_tpu.telemetry.sinks import ListSink


# --- bucketing math ----------------------------------------------------------

def test_pow2_bucket_selection():
    assert pow2_bucket(1, 1, 64) == 1
    assert pow2_bucket(3, 1, 64) == 4
    assert pow2_bucket(4, 1, 64) == 4
    assert pow2_bucket(5, 1, 64) == 8
    assert pow2_bucket(33, 1, 64) == 64
    assert pow2_bucket(2, 8, 64) == 8      # clamped to the floor
    with pytest.raises(mx.MXNetError):
        pow2_bucket(65, 1, 64)             # over the ceiling rejects


def test_bucket_policy_signature_space():
    p = BucketPolicy(max_batch=4, max_length=64, min_batch=1, min_length=8)
    assert p.batch_buckets() == [1, 2, 4]
    assert p.length_buckets() == [8, 16, 32, 64]
    assert len(p.signatures()) == 12
    assert p.batch_bucket(3) == 4
    assert p.length_bucket(17) == 32
    # every bucketed shape is a member of the enumerated space
    for n in range(1, 5):
        for l in range(1, 65):
            assert (p.batch_bucket(n), p.length_bucket(l)) \
                in p.signatures()


def test_pad_batch_shapes_and_errors():
    exs = [np.ones((3, 5)), 2 * np.ones((7, 5))]
    b = pad_batch(exs, 4, 8)
    assert b.shape == (4, 8, 5)
    assert np.array_equal(b[0, :3], exs[0])
    assert np.array_equal(b[1, :7], exs[1])
    assert (b[0, 3:] == 0).all()           # length padding is zeros
    assert np.array_equal(b[2], b[0])      # vacant rows repeat row 0
    with pytest.raises(mx.MXNetError):
        pad_batch(exs, 1, 8)               # too many examples
    with pytest.raises(mx.MXNetError):
        pad_batch(exs, 4, 4)               # length over bucket
    with pytest.raises(mx.MXNetError):
        pad_batch([], 4, 8)


# --- a shape-polymorphic position-wise model for bit-identity tests ----------

def _positionwise_predictor(tmp_path, in_dim=6, hidden=5):
    """nnvm FullyConnected(flatten=False) chain: every (batch, length)
    row is an independent gemm row, so padded forwards are bit-identical
    to unpadded ones on the real rows."""
    import mxnet_tpu.symbol as sym

    data = sym.Variable("data")
    w = sym.Variable("fc_weight")
    b = sym.Variable("fc_bias")
    out = sym.FullyConnected(data, w, b, num_hidden=hidden, flatten=False,
                             name="fc")
    out = sym.Activation(out, act_type="relu")
    rs = np.random.RandomState(7)
    wv = rs.randn(hidden, in_dim).astype(np.float32)
    bv = rs.randn(hidden).astype(np.float32)
    prefix = str(tmp_path / "posw")
    out.save(f"{prefix}-symbol.json")
    serialization.save_ndarrays(f"{prefix}-0000.params", {
        "arg:fc_weight": nd.array(wv), "arg:fc_bias": nd.array(bv)})
    pred = Predictor(f"{prefix}-symbol.json", f"{prefix}-0000.params")
    oracle = lambda x: np.maximum(x @ wv.T + bv, 0.0)  # noqa: E731
    return pred, oracle


def test_padding_bit_identity_vs_unpadded_oracle(tmp_path):
    """The demuxed rows of a padded, bucketed batch forward are
    BIT-identical to each request's own unbatched forward."""
    pred, _ = _positionwise_predictor(tmp_path)
    rs = np.random.RandomState(3)
    exs = [rs.randn(l, 6).astype(np.float32) for l in (3, 7, 5)]
    batch = pad_batch(exs, 4, 8)
    padded = pred.predict(batch).asnumpy()
    for i, x in enumerate(exs):
        solo = pred.predict(x[None]).asnumpy()[0]
        assert np.array_equal(padded[i, :len(x)], solo)


# --- KV cache slot ledger ----------------------------------------------------

def test_kv_cache_admit_evict_invariants():
    m = KVCacheManager(3, 32)
    s = [m.admit(i, 4, 8) for i in range(3)]
    assert sorted(s) == [0, 1, 2]
    assert m.admit(9, 4, 8) is None        # at capacity: admission defers
    assert m.free_slots() == 0
    m.check()
    m.advance(s[0])
    assert m.state(s[0]).pos == 5
    assert not m.consume(s[0])
    for _ in range(7):
        done = m.consume(s[0])
    assert done                             # budget of 8 spent
    m.evict(s[0])
    m.check()
    assert m.free_slots() == 1
    with pytest.raises(mx.MXNetError):
        m.evict(s[0])                       # double evict
    with pytest.raises(mx.MXNetError):
        m.admit(9, 30, 8)                   # 30+8 > max_len 32


def test_kv_cache_slot_reuse():
    m = KVCacheManager(2, 64)
    a = m.admit(1, 4, 4)
    b = m.admit(2, 4, 4)
    m.evict(a)
    c = m.admit(3, 8, 4)
    assert c == a                           # freed slot is reused
    assert m.state(c).request_id == 3
    assert m.state(c).pos == 8              # fresh position, no leakage
    m.evict(b)
    m.evict(c)
    m.check()
    st = m.stats()
    assert st["admits"] == 3 and st["evictions"] == 3
    assert st["peak_occupancy"] == 2 and st["occupancy"] == 0


# --- backpressure ------------------------------------------------------------

def test_bounded_queue_backpressure():
    q = RequestQueue(capacity=2)
    q.put(Request(inputs={}, length=1))
    q.put(Request(inputs={}, length=1))
    with pytest.raises(ServerOverloadedError):
        q.put(Request(inputs={}, length=1))
    assert q.rejected == 1
    q.close()
    with pytest.raises(ServerClosedError):
        q.put(Request(inputs={}, length=1))


def test_submit_requires_running_server(tmp_path):
    pred, _ = _positionwise_predictor(tmp_path)
    srv = serving.InferenceServer(pred, ServerConfig(max_batch=2))
    with pytest.raises(ServerClosedError):
        srv.submit(np.zeros((4, 6), np.float32))


# --- telemetry rolling histograms -------------------------------------------

def test_telemetry_rolling_histogram():
    telemetry.enable(memory=False, cost=False)
    try:
        for v in range(1, 101):
            telemetry.hist("t.lat", float(v), cap=10)
        s = telemetry.hist_summary("t.lat")
        # window keeps only the last 10 of 100 observations
        assert s["count"] == 100 and s["window"] == 10
        assert s["p50"] == 95.0 and s["p99"] == 100.0
        assert s["min"] == 1.0 and s["max"] == 100.0
        assert "t.lat" in telemetry.hists()
        assert telemetry.hist_summary("absent") is None
    finally:
        telemetry.disable()
    # disabled -> no-op, no state
    telemetry.hist("t.off", 1.0)
    assert telemetry.hist_summary("t.off") is None


def test_telemetry_emit_to_sinks():
    telemetry.enable(memory=False, cost=False)
    sink = ListSink()
    telemetry.add_sink(sink)
    try:
        rec = telemetry.emit({"record": "x", "v": 1})
        assert rec == {"record": "x", "v": 1}
        assert sink.records == [rec]
    finally:
        telemetry.disable()
    assert telemetry.emit({"record": "y"}) is None


# --- the acceptance paths ----------------------------------------------------

def test_multi_client_continuous_batching_end_to_end(tmp_path):
    """Concurrent mixed-length clients; <=4 compiled signatures
    (predictor cache stats), bit-identical results, per-request JSONL
    records and a rolling serving.latency summary."""
    pred, oracle = _positionwise_predictor(tmp_path)
    telemetry.enable(memory=False, cost=False)
    sink = ListSink()
    telemetry.add_sink(sink)
    cfg = ServerConfig(max_batch=4, max_length=16, min_batch=2,
                       min_length=8, output_length_axis=0,
                       batch_window_ms=10.0, summary_every=4)
    srv = serving.InferenceServer(pred, cfg)
    rs = np.random.RandomState(11)
    lengths = [3, 5, 9, 7, 12, 4, 8, 15, 2, 6, 11, 16]
    inputs = [rs.randn(l, 6).astype(np.float32) for l in lengths]
    results = [None] * len(inputs)

    def client(i):
        results[i] = srv.infer(inputs[i], timeout=60.0)

    try:
        with srv:
            threads = [threading.Thread(target=client, args=(i,))
                       for i in range(len(inputs))]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
        stats = srv.stats()
    finally:
        telemetry.disable()

    # bit-identity: every demuxed result equals the unbatched oracle
    for x, got in zip(inputs, results):
        assert got.shape == (len(x), 5)
        assert np.array_equal(got, oracle(x))
    # bucketing held: two length buckets x two batch buckets at most
    assert 1 <= stats["cache"]["signatures"] <= 4
    assert stats["cache"]["misses"] == stats["cache"]["signatures"]
    assert stats["completed"] == len(inputs)
    # dynamic batching actually batched (not all head-of-line singletons)
    assert stats["batches"] < len(inputs)
    # JSONL stream: per-request records with the span fields
    recs = [r for r in sink.records if r.get("record") == "serving.request"]
    assert len(recs) == len(inputs)
    for r in recs:
        assert r["queue_wait_ms"] >= 0.0
        assert r["total_ms"] > 0.0
        assert r["batch_size"] >= 1
        assert tuple(r["bucket"]) in {(b, l) for b, l
                                      in cfg.policy.signatures()}
    assert any(r["batch_size"] > 1 for r in recs)
    # rolling latency summary landed with percentiles
    sums = [r for r in sink.records if r.get("record") == "serving.latency"]
    assert sums
    last = sums[-1]
    assert last["total_ms"]["p50"] <= last["total_ms"]["p99"]
    assert last["batch_size"]["max"] > 1


def test_generative_late_join_and_parity():
    """A late request joins the in-flight decode batch (continuous
    batching) and both results match the offline generate() oracle
    token for token.  Runs the legacy slot-ledger A/B path
    (``kv_mode="slots"``): its single scheduler loop interleaves
    prefill with decode, so the done_step ordering below is exact."""
    from mxnet_tpu.models.llama import llama_tiny

    net = llama_tiny()
    net.initialize()
    telemetry.enable(memory=False, cost=False)
    sink = ListSink()
    telemetry.add_sink(sink)
    rs = np.random.RandomState(0)
    p1 = rs.randint(1, 250, size=5)
    p2 = rs.randint(1, 250, size=9)
    cfg = ServerConfig(max_batch=2, max_length=64, min_length=8,
                       num_slots=2, summary_every=2, kv_mode="slots")
    srv = serving.GenerativeServer(net, cfg)
    try:
        with srv:
            f1 = srv.submit(p1, max_new_tokens=40)
            # wait until request 1 is actually decoding, then join late
            deadline = time.time() + 60
            while srv.engine.steps < 2 and time.time() < deadline:
                time.sleep(0.01)
            assert srv.engine.steps >= 2
            f2 = srv.submit(p2, max_new_tokens=4)
            r1 = f1.result(120)
            r2 = f2.result(120)
        stats = srv.stats()
    finally:
        telemetry.disable()

    o1 = net.generate(nd.array(p1[None]), 40).asnumpy()[0]
    o2 = net.generate(nd.array(p2[None]), 4).asnumpy()[0]
    assert np.array_equal(r1, o1)
    assert np.array_equal(r2, o2)

    recs = {r["request_id"]: r for r in sink.records
            if r.get("record") == "serving.request"}
    assert len(recs) == 2
    r1rec = min(recs.values(), key=lambda r: r["request_id"])
    r2rec = max(recs.values(), key=lambda r: r["request_id"])
    # the late request was admitted AFTER decode began and finished
    # BEFORE the long request: it joined the in-flight batch
    assert r2rec["joined_step"] >= 2
    assert r2rec["done_step"] < r1rec["done_step"]
    assert r1rec["ttft_ms"] > 0 and r2rec["ttft_ms"] > 0
    # both sequences shared slots concurrently
    assert stats["kv_cache"]["peak_occupancy"] == 2
    assert stats["kv_cache"]["occupancy"] == 0
    # one step signature ever, prefill per prompt bucket
    sigs = stats["compiled_signatures"]
    assert sigs.count(("step",)) == 1
    assert len([s for s in sigs if s[0] == "prefill"]) <= 2
    # rolling summary carries ttft percentiles for generative traffic
    sums = [r for r in sink.records if r.get("record") == "serving.latency"]
    assert sums and sums[-1]["ttft_ms"] is not None


def test_generative_paged_lanes_late_join_and_parity():
    """The default (paged KV + disaggregated lanes) path: a late
    request is prefilled by the prefill lane and handed off to the
    decode lane WITHOUT stalling the in-flight decode, both results
    are token-exact vs offline generate(), and the request records
    carry the lane fields (replica / kv_blocks / handoff_ms)."""
    from mxnet_tpu.models.llama import llama_tiny

    net = llama_tiny()
    net.initialize()
    telemetry.enable(memory=False, cost=False)
    sink = ListSink()
    telemetry.add_sink(sink)
    rs = np.random.RandomState(0)
    p1 = rs.randint(1, 250, size=5)
    p2 = rs.randint(1, 250, size=9)
    cfg = ServerConfig(max_batch=2, max_length=64, min_length=8,
                       num_slots=2, summary_every=2)
    srv = serving.GenerativeServer(net, cfg)
    try:
        with srv:
            # warm both prefill buckets so the late join below isn't
            # skewed by first-compile time (decode does NOT stall for
            # prefill in the lanes path — that's the point of it)
            srv.generate(p1, max_new_tokens=2)
            srv.generate(p2, max_new_tokens=2)
            base = srv.engine.steps
            f1 = srv.submit(p1, max_new_tokens=40)
            deadline = time.time() + 60
            while srv.engine.steps < base + 2 and time.time() < deadline:
                time.sleep(0.01)
            assert srv.engine.steps >= base + 2
            f2 = srv.submit(p2, max_new_tokens=4)
            r1 = f1.result(120)
            r2 = f2.result(120)
        stats = srv.stats()
    finally:
        telemetry.disable()

    o1 = net.generate(nd.array(p1[None]), 40).asnumpy()[0]
    o2 = net.generate(nd.array(p2[None]), 4).asnumpy()[0]
    assert np.array_equal(r1, o1)
    assert np.array_equal(r2, o2)

    recs = [r for r in sink.records if r.get("record") == "serving.request"]
    assert len(recs) == 4
    r1rec, r2rec = recs[-2], recs[-1]
    if r1rec["request_id"] > r2rec["request_id"]:
        r1rec, r2rec = r2rec, r1rec
    # the late request joined mid-flight and (its prefill being warm)
    # finished its 4 tokens long before the 40-token request
    assert r2rec["joined_step"] >= base + 2
    assert r2rec["done_step"] < r1rec["done_step"]
    assert r1rec["ttft_ms"] > 0 and r2rec["ttft_ms"] > 0
    # lane fields: served by replica 0, KV block budget reserved up
    # front (5+40 tokens -> 3 blocks of 16), handoff measured
    for rec in (r1rec, r2rec):
        assert rec["replica"] == 0
        assert rec["lane"] == "decode"
        assert rec["handoff_ms"] >= 0
    assert r1rec["kv_blocks"] == 3
    assert r2rec["kv_blocks"] == 1
    # slots shared concurrently; pool fully returned at drain
    assert stats["kv_cache"]["peak_occupancy"] == 2
    assert stats["kv_cache"]["occupancy"] == 0
    assert stats["kv_cache"]["blocks_in_use"] == 0
    assert stats["kv_cache"]["peak_blocks_in_use"] >= 4
    # ONE decode-step signature for the server lifetime, prefill per
    # prompt bucket
    sigs = stats["compiled_signatures"]
    assert sigs.count(("step",)) == 1
    assert len([s for s in sigs if s[0] == "prefill"]) <= 2
    # rolling summary carries the handoff percentiles
    sums = [r for r in sink.records if r.get("record") == "serving.latency"]
    assert sums and sums[-1]["handoff_ms"] is not None
    assert sums[-1]["kv_cache"]["block_size"] == 16


def test_generative_int8_load_option():
    """int8 weight quantization at load time: the engine decodes and
    honors shapes (no parity claim vs fp32)."""
    from mxnet_tpu.models.llama import llama_tiny

    net = llama_tiny()
    net.initialize()
    rs = np.random.RandomState(1)
    prompt = rs.randint(1, 250, size=6)
    cfg = ServerConfig(max_batch=2, max_length=64, min_length=8,
                       num_slots=2, int8=True)
    srv = serving.GenerativeServer(net, cfg)
    assert srv.engine.int8
    # weights really are int8 on device
    q = srv.engine._w["layers"][0]["q"]
    assert str(q["q8"].dtype) == "int8"
    with srv:
        out = srv.generate(prompt, max_new_tokens=5)
    assert out.shape == (len(prompt) + 5,)
    assert np.array_equal(out[:len(prompt)], prompt)
    assert (out < net.config.vocab_size).all()


# --- paged KV: block allocator + manager invariants --------------------------

def test_block_allocator_invariants():
    """All-or-nothing allocation, no double-assignment, double-free
    raises, and a full alloc/free round-trip restores the pool."""
    from mxnet_tpu.serving import BlockAllocator

    a = BlockAllocator(num_blocks=6, block_size=16)
    assert a.free_blocks == 6 and a.blocks_in_use == 0
    b1 = a.alloc(4)
    b2 = a.alloc(2)
    assert len(b1) == 4 and len(b2) == 2
    # no block handed out twice
    assert len(set(b1) | set(b2)) == 6
    assert a.free_blocks == 0 and a.blocks_in_use == 6
    # all-or-nothing: an empty pool refuses, state unchanged
    assert a.alloc(1) is None
    assert a.free_blocks == 0
    a.free(b2)
    assert a.free_blocks == 2 and a.peak_blocks_in_use == 6
    with pytest.raises(mx.MXNetError):
        a.free(b2)                         # double-free
    a.free(b1)
    assert a.free_blocks == 6 and a.blocks_in_use == 0
    a.check()
    # round-trip: the pool serves the full count again
    assert len(a.alloc(6)) == 6


def test_paged_manager_admit_advance_evict():
    """Upfront block reservation sized by prompt+budget; advancing past
    the reservation raises; eviction returns every block."""
    from mxnet_tpu.serving import PagedKVCacheManager

    mgr = PagedKVCacheManager(num_slots=2, max_len=64, num_blocks=8,
                              block_size=16)
    assert mgr.blocks_for(9, 4) == 1       # 13 tokens -> 1 block
    assert mgr.blocks_for(9, 8) == 2       # 17 tokens -> 2 blocks
    slot, blocks = mgr.admit("r1", 17, 15)  # 32 tokens -> 2 blocks
    assert len(blocks) == 2
    assert mgr.allocator.blocks_in_use == 2
    for _ in range(15):
        mgr.advance(slot)
    with pytest.raises(mx.MXNetError):
        mgr.advance(slot)                  # past the 32-token reserve
    mgr.evict(slot)
    assert mgr.allocator.blocks_in_use == 0
    mgr.check()
    st = mgr.stats()
    assert st["capacity_tokens"] == 8 * 16
    assert st["peak_tokens"] >= 17
    assert st["tokens_in_flight"] == 0


def test_legacy_ledger_stats_fields():
    """The r8 slot ledger stays importable for A/B and now reports the
    same occupancy vocabulary as the paged manager: capacity in tokens,
    tokens in flight, peak tokens, fragmentation."""
    mgr = KVCacheManager(num_slots=2, max_len=32)
    s0 = mgr.stats()
    assert s0["capacity_tokens"] == 64
    assert s0["tokens_in_flight"] == 0 and s0["fragmentation"] == 0.0
    slot = mgr.admit("r1", prompt_len=10, max_new_tokens=4)
    st = mgr.stats()
    # the ledger reserves max_len per occupied slot: 10 live tokens out
    # of a 32-token reservation is mostly fragmentation
    assert st["tokens_in_flight"] == 10
    assert st["peak_tokens"] == 10
    assert st["fragmentation"] == pytest.approx(1 - 10 / 32, abs=1e-4)
    mgr.evict(slot)
    assert mgr.stats()["tokens_in_flight"] == 0


def test_paged_capacity_beats_ledger():
    """The acceptance mix: a pool whose worst-case ``slots × max_len``
    exceeds its token capacity still admits (and correctly serves) all
    four short requests — the equal-byte ledger holds two."""
    from mxnet_tpu.models.llama import llama_tiny
    from mxnet_tpu.serving import PagedKVCacheManager

    # manager level: 8 blocks × 16 = 128 tokens backs FOUR slots whose
    # worst case is 4 × 64 = 256; the 128-token ledger holds TWO slots
    mgr = PagedKVCacheManager(num_slots=4, max_len=64, num_blocks=8,
                              block_size=16)
    admits = [mgr.admit(i, 9, 4) for i in range(4)]   # 13 tokens each
    assert all(a is not None for a in admits)
    assert mgr.stats()["occupancy"] == 4
    ledger = KVCacheManager(num_slots=2, max_len=64)  # same 128 tokens
    assert ledger.admit("a", 9, 4) is not None
    assert ledger.admit("b", 9, 4) is not None
    assert ledger.admit("c", 9, 4) is None            # full
    for slot, _ in admits:
        mgr.evict(slot)
    assert mgr.allocator.free_blocks == 8
    mgr.check()

    # server level: the undersized pool serves the same mix token-exact
    # vs the r8 slots path
    net = llama_tiny()
    net.initialize()
    rs = np.random.RandomState(3)
    prompts = [rs.randint(1, 250, size=9) for _ in range(4)]
    oracle_cfg = ServerConfig(max_batch=4, max_length=64, min_length=8,
                              num_slots=4, kv_mode="slots")
    with serving.GenerativeServer(net, oracle_cfg) as oracle:
        want = [oracle.generate(p, max_new_tokens=4) for p in prompts]
    cfg = ServerConfig(max_batch=4, max_length=64, min_length=8,
                       num_slots=4, num_blocks=8, block_size=16)
    srv = serving.GenerativeServer(net, cfg)
    assert srv.engine.num_blocks == 8
    with srv:
        futs = [srv.submit(p, max_new_tokens=4) for p in prompts]
        got = [f.result(120) for f in futs]
        stats = srv.stats()
    for g, w in zip(got, want):
        assert np.array_equal(g, w)
    kv = stats["kv_cache"]
    assert kv["capacity_tokens"] == 128        # < 4 slots × 64 worst case
    assert kv["admits"] == 4
    assert kv["peak_occupancy"] >= 3           # served concurrently
    assert kv["blocks_in_use"] == 0 and kv["occupancy"] == 0


def test_generative_server_mesh_dp2_tp2_token_exact():
    """dp2×tp2 CPU mesh: weights tensor-parallel per replica, two
    independent replicas behind one queue.  Token-exact vs the
    single-device r8 slots path, ONE decode compile per replica, both
    replicas take work, and the engine's pool bytes match the memory
    planner's ``plan_kv_pool`` on the tp submesh."""
    import jax
    from jax.sharding import Mesh
    from mxnet_tpu.memory import plan_kv_pool
    from mxnet_tpu.models.llama import llama_tiny

    if len(jax.devices()) < 4:
        pytest.skip("needs 4 devices (dp2×tp2)")
    net = llama_tiny()
    net.initialize()
    rs = np.random.RandomState(5)
    prompts = [rs.randint(1, 250, size=n) for n in (5, 9, 12, 7)]
    oracle_cfg = ServerConfig(max_batch=2, max_length=64, min_length=8,
                              num_slots=2, kv_mode="slots")
    with serving.GenerativeServer(net, oracle_cfg) as oracle:
        want = [oracle.generate(p, max_new_tokens=6) for p in prompts]

    mesh = Mesh(np.array(jax.devices()[:4]).reshape(2, 2), ("dp", "tp"))
    cfg = ServerConfig(max_batch=2, max_length=64, min_length=8,
                       num_slots=2, summary_every=4)
    srv = serving.GenerativeServer(net, cfg, mesh=mesh)
    with srv:
        futs = [srv.submit(p, max_new_tokens=6) for p in prompts]
        got = [f.result(120) for f in futs]
        stats = srv.stats()
    for g, w in zip(got, want):
        assert np.array_equal(g, w)

    assert stats["num_replicas"] == 2
    # least-loaded routing spread the burst over both replicas
    per_rep = stats["replicas"]
    assert len(per_rep) == 2
    assert all(r["completed"] >= 1 for r in per_rep)
    assert sum(r["completed"] for r in per_rep) == 4
    # one decode compile per replica for the whole lifetime
    for rep in srv.replicas:
        sigs = rep.engine.compiled_signatures()
        assert sigs.count(("step",)) == 1
    # pool placement agrees with the planner on the tp submesh
    tp_mesh = Mesh(np.array(jax.devices()[:2]), ("tp",))
    eng = srv.replicas[0].engine
    assert eng.kv_pool_bytes() == plan_kv_pool(
        net.config.num_layers, net.config.num_kv_heads,
        net.config.head_dim, num_blocks=eng.num_blocks,
        block_size=eng.block_size, mesh=tp_mesh)
