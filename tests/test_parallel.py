"""Distributed-layer tests on the virtual 8-device CPU mesh.

The TPU analog of the reference's localhost multi-process kvstore tests
(tests/nightly/dist_sync_kvstore.py:? — spawn N roles on localhost, assert
replica consistency).  Here XLA's CPU backend provides 8 fake devices and
GSPMD is exercised for real: sharded batches, replicated params, derived
gradient all-reduce.
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd, parallel
from mxnet_tpu.gluon import nn


@pytest.fixture
def mesh():
    m = parallel.make_mesh({"dp": 8})
    with parallel.mesh_scope(m):
        yield m


def _make_net(seed=11):
    mx.random.seed(seed)
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(16, activation="relu", in_units=8),
                nn.Dense(4, in_units=16))
    net.initialize(mx.init.Xavier())
    return net


def test_make_mesh_shapes():
    m = parallel.make_mesh({"dp": 4, "tp": 2})
    assert m.shape == {"dp": 4, "tp": 2}
    m1 = parallel.make_mesh()
    assert m1.shape == {"dp": 8}


def test_shard_batch_layout(mesh):
    x = nd.ones((16, 8))
    xs = parallel.shard_batch(x)
    assert xs.shape == (16, 8)
    # 8 shards of 2 rows each
    db = xs._data.sharding.device_set
    assert len(db) == 8


def test_split_and_load_returns_single_sharded(mesh):
    ctxs = [mx.cpu(i) for i in range(8)]
    parts = gluon.utils.split_and_load(nd.ones((16, 4)), ctxs)
    assert len(parts) == 1
    assert parts[0].shape == (16, 4)


def test_dp_grads_match_single_device(mesh):
    """The core GSPMD claim: sharded-batch training computes the SAME
    gradients as single-device full-batch training."""
    x_np = np.random.RandomState(0).rand(16, 8).astype(np.float32)
    y_np = np.random.RandomState(1).randint(0, 4, (16,))

    # single-device reference
    net1 = _make_net()
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    with autograd.record():
        l1 = loss_fn(net1(nd.array(x_np)), nd.array(y_np)).sum()
    l1.backward()
    ref_grads = {k: p.grad().asnumpy()
                 for k, p in net1.collect_params().items()}

    # mesh data-parallel
    net2 = _make_net()
    parallel.replicate_block_params(net2)
    net2.hybridize()
    xs = parallel.shard_batch(nd.array(x_np))
    ys = parallel.shard_batch(nd.array(y_np))
    with autograd.record():
        l2 = loss_fn(net2(xs), ys).sum()
    l2.backward()
    assert np.allclose(float(l1.asscalar()), float(l2.asscalar()), atol=1e-4)
    for (k, p), (k2, p2) in zip(net1.collect_params().items(),
                                net2.collect_params().items()):
        assert np.allclose(ref_grads[k], p2.grad().asnumpy(), atol=1e-4), k


def test_dist_tpu_sync_trainer_step(mesh):
    net = _make_net()
    parallel.replicate_block_params(net)
    net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1},
                            kvstore="dist_tpu_sync")
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    x = parallel.shard_batch(mx.random.uniform(shape=(32, 8)))
    y = parallel.shard_batch(nd.array(np.arange(32) % 4))
    losses = []
    for _ in range(12):
        with autograd.record():
            loss = loss_fn(net(x), y)
        loss.backward()
        trainer.step(32)
        losses.append(float(loss.mean().asscalar()))
    assert losses[-1] < losses[0]
    assert trainer._kvstore.type == "dist_tpu_sync"
    assert trainer._kvstore.num_devices == 8


def test_dist_sync_alias_warns(mesh):
    with pytest.warns(UserWarning):
        kv = mx.kv.create("dist_sync")
    assert kv.type == "dist_tpu_sync"


def test_dp_training_converges_same_as_single(mesh):
    """Train the same net both ways for 10 steps; weights must track."""
    x_np = np.random.RandomState(2).rand(16, 8).astype(np.float32)
    y_np = (x_np @ np.random.RandomState(3).rand(8, 4)).astype(np.float32)
    loss_fn = gluon.loss.L2Loss()

    nets = []
    for mode in ("single", "mesh"):
        net = _make_net()
        if mode == "mesh":
            parallel.replicate_block_params(net)
            net.hybridize()
            x = parallel.shard_batch(nd.array(x_np))
            y = parallel.shard_batch(nd.array(y_np))
        else:
            x, y = nd.array(x_np), nd.array(y_np)
        trainer = gluon.Trainer(
            net.collect_params(), "sgd", {"learning_rate": 0.05},
            kvstore="dist_tpu_sync" if mode == "mesh" else None)
        for _ in range(10):
            with autograd.record():
                loss = loss_fn(net(x), y)
            loss.backward()
            trainer.step(16)
        nets.append(net)
    for (k, p1), (_, p2) in zip(nets[0].collect_params().items(),
                                nets[1].collect_params().items()):
        assert np.allclose(p1.data().asnumpy(), p2.data().asnumpy(),
                           atol=1e-3), k


def test_tensor_parallel_shard_param():
    m = parallel.make_mesh({"dp": 2, "tp": 4})
    with parallel.mesh_scope(m):
        dense = nn.Dense(8, in_units=4)
        dense.initialize()
        parallel.shard_param(dense.weight, ("tp", None))
        parallel.replicate(dense.bias.data())
        x = parallel.replicate(nd.ones((2, 4)))
        out = dense(x)
        assert out.shape == (2, 8)
        # sharding survived placement
        names = dense.weight.data()._data.sharding.spec
        assert names[0] == "tp"


def test_multihost_initialize_noop():
    parallel.initialize()  # single-process: returns without touching jax
