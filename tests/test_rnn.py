"""RNN tests (reference: tests/python/unittest/test_gluon_rnn.py:? —
cell-vs-fused-layer consistency is the core check)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd
from mxnet_tpu.gluon import rnn


def test_rnn_cell_step():
    cell = rnn.RNNCell(8, input_size=4)
    cell.initialize()
    x = mx.random.uniform(shape=(3, 4))
    states = cell.begin_state(3)
    out, new_states = cell(x, states)
    assert out.shape == (3, 8)
    assert new_states[0].shape == (3, 8)


def test_lstm_cell_unroll():
    cell = rnn.LSTMCell(6, input_size=5)
    cell.initialize()
    x = mx.random.uniform(shape=(2, 7, 5))  # NTC
    outputs, states = cell.unroll(7, x, layout="NTC")
    assert len(outputs) == 7
    assert outputs[0].shape == (2, 6)
    assert len(states) == 2


def test_gru_cell_deferred_input():
    cell = rnn.GRUCell(4)
    cell.initialize()
    out, states = cell(nd.ones((2, 3)), cell.begin_state(2))
    assert out.shape == (2, 4)
    assert cell.i2h_weight.shape == (12, 3)


def test_sequential_cell():
    stack = rnn.SequentialRNNCell()
    stack.add(rnn.LSTMCell(4, input_size=3))
    stack.add(rnn.LSTMCell(5, input_size=4))
    stack.initialize()
    states = stack.begin_state(2)
    out, new_states = stack(nd.ones((2, 3)), states)
    assert out.shape == (2, 5)
    assert len(new_states) == 4


def test_residual_cell():
    cell = rnn.ResidualCell(rnn.RNNCell(4, input_size=4))
    cell.initialize()
    out, _ = cell(nd.ones((2, 4)), cell.begin_state(2))
    assert out.shape == (2, 4)


def test_lstm_layer_matches_cell():
    """Fused LSTM layer must agree with stepping the cell (the reference's
    fused-op-vs-cell consistency test)."""
    layer = rnn.LSTM(6, input_size=5)
    layer.initialize()
    x = mx.random.uniform(shape=(4, 2, 5))  # TNC
    out, states = layer(x, layer.begin_state(2))
    assert out.shape == (4, 2, 6)
    assert states[0].shape == (1, 2, 6)

    cell = rnn.LSTMCell(6, input_size=5)
    cell.initialize()
    cell.i2h_weight.set_data(layer.l0_i2h_weight.data())
    cell.h2h_weight.set_data(layer.l0_h2h_weight.data())
    cell.i2h_bias.set_data(layer.l0_i2h_bias.data())
    cell.h2h_bias.set_data(layer.l0_h2h_bias.data())
    h = [nd.zeros((2, 6)), nd.zeros((2, 6))]
    outs = []
    for t in range(4):
        o, h = cell(x[t], h)
        outs.append(o.asnumpy())
    assert np.allclose(out.asnumpy(), np.stack(outs), atol=1e-5)
    assert np.allclose(states[0].asnumpy()[0], outs[-1], atol=1e-5)


def test_gru_layer_matches_cell():
    layer = rnn.GRU(4, input_size=3)
    layer.initialize()
    x = mx.random.uniform(shape=(3, 2, 3))
    out, _ = layer(x, layer.begin_state(2))

    cell = rnn.GRUCell(4, input_size=3)
    cell.initialize()
    cell.i2h_weight.set_data(layer.l0_i2h_weight.data())
    cell.h2h_weight.set_data(layer.l0_h2h_weight.data())
    cell.i2h_bias.set_data(layer.l0_i2h_bias.data())
    cell.h2h_bias.set_data(layer.l0_h2h_bias.data())
    h = [nd.zeros((2, 4))]
    outs = []
    for t in range(3):
        o, h = cell(x[t], h)
        outs.append(o.asnumpy())
    assert np.allclose(out.asnumpy(), np.stack(outs), atol=1e-5)


def test_lstm_layer_ntc_and_no_states():
    layer = rnn.LSTM(8, num_layers=2, layout="NTC", input_size=4)
    layer.initialize()
    out = layer(nd.ones((3, 5, 4)))
    assert out.shape == (3, 5, 8)


def test_bidirectional_lstm_layer():
    layer = rnn.LSTM(4, bidirectional=True, input_size=3)
    layer.initialize()
    out, states = layer(mx.random.uniform(shape=(5, 2, 3)),
                        layer.begin_state(2))
    assert out.shape == (5, 2, 8)
    assert states[0].shape == (2, 2, 4)


def test_rnn_layer_backward():
    layer = rnn.LSTM(6, input_size=5)
    layer.initialize()
    x = mx.random.uniform(shape=(4, 2, 5))
    with autograd.record():
        out = layer(x)
        loss = out.sum()
    loss.backward()
    g = layer.l0_i2h_weight.grad()
    assert np.abs(g.asnumpy()).sum() > 0


def test_rnn_layer_hybridize():
    layer = rnn.GRU(5, num_layers=2, input_size=4)
    layer.initialize()
    x = mx.random.uniform(shape=(3, 2, 4))
    imp = layer(x).asnumpy()
    layer.hybridize()
    hyb = layer(x).asnumpy()
    assert np.allclose(imp, hyb, atol=1e-5)


def test_rnn_relu_layer():
    layer = rnn.RNN(4, activation="relu", input_size=3)
    layer.initialize()
    out = layer(nd.ones((2, 2, 3)))
    assert out.shape == (2, 2, 4)
    assert (out.asnumpy() >= 0).all()
