"""Model-zoo tests (reference: tests/python/unittest/test_gluon_model_zoo.py:?
— construct every model, forward-check representative ones)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd
from mxnet_tpu.gluon.model_zoo import vision


@pytest.mark.parametrize("name", [
    "resnet18_v1", "resnet50_v1", "resnet18_v2", "resnet50_v2",
    "vgg11", "vgg11_bn", "alexnet", "densenet121", "squeezenet1.0",
    "squeezenet1.1", "mobilenet1.0", "mobilenet0.25", "mobilenetv2_1.0",
    "inceptionv3",
])
def test_models_construct(name):
    net = vision.get_model(name, classes=10)
    params = net.collect_params()
    assert len(params) > 0


def test_get_model_unknown():
    with pytest.raises(Exception):
        vision.get_model("resnet9999")


def test_resnet18_forward_and_backward():
    net = vision.resnet18_v1(classes=10)
    net.initialize()
    x = mx.random.uniform(shape=(2, 3, 32, 32))
    with autograd.record():
        out = net(x)
        loss = out.sum()
    loss.backward()
    assert out.shape == (2, 10)
    g = net.features[0].weight.grad()
    assert np.abs(g.asnumpy()).sum() > 0


def test_resnet18_v2_forward():
    net = vision.resnet18_v2(classes=7)
    net.initialize()
    out = net(nd.ones((1, 3, 32, 32)))
    assert out.shape == (1, 7)


def test_resnet_thumbnail():
    net = vision.get_model("resnet18_v1", classes=10, thumbnail=True)
    net.initialize()
    out = net(nd.ones((2, 3, 32, 32)))
    assert out.shape == (2, 10)


def test_mobilenet_forward():
    net = vision.mobilenet0_25(classes=5)
    net.initialize()
    out = net(nd.ones((1, 3, 64, 64)))
    assert out.shape == (1, 5)


def test_squeezenet_forward():
    net = vision.squeezenet1_1(classes=4)
    net.initialize()
    out = net(nd.ones((1, 3, 64, 64)))
    assert out.shape == (1, 4)


def test_resnet_hybridized_training_step():
    net = vision.resnet18_v1(classes=10, thumbnail=True)
    net.initialize(mx.init.Xavier())
    net.hybridize(static_alloc=True)
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1, "momentum": 0.9})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    x = mx.random.uniform(shape=(4, 3, 32, 32))
    y = nd.array([0, 1, 2, 3])
    losses = []
    for _ in range(5):
        with autograd.record():
            loss = loss_fn(net(x), y)
        loss.backward()
        trainer.step(4)
        losses.append(float(loss.mean().asscalar()))
    assert losses[-1] < losses[0]
