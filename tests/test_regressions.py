"""Regression tests for review findings (kept permanently, reference model:
the reference's targeted regression tests inside test_operator.py)."""
import math

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import nd, autograd


def test_setitem_ndarray_integer_key():
    # MXNet 1.x semantics: float/int NDArray keys index (take-style)
    x = nd.array([1.0, 2.0, 3.0])
    x[nd.array([2, 0], dtype="int32")] = 0
    np.testing.assert_allclose(x.asnumpy(), [0.0, 2.0, 0.0])


def test_setitem_bool_mask_key():
    y = nd.array([1.0, 2.0, 3.0])
    y[np.array([False, True, True])] = 9
    np.testing.assert_allclose(y.asnumpy(), [1.0, 9.0, 9.0])


def test_full_overwrite_retapes():
    a = nd.array([1.0, 1.0])
    a.attach_grad()
    b = nd.array([5.0, 5.0])
    b.attach_grad()
    with autograd.record():
        y = a * 2
        y[:] = b
        (y * 1).sum().backward()
    np.testing.assert_allclose(a.grad.asnumpy(), [0.0, 0.0])
    np.testing.assert_allclose(b.grad.asnumpy(), [1.0, 1.0])


def test_float_index_from_argmax():
    x = nd.array([3.0, 1.0, 2.0])
    assert float(x[x.argmax()].asscalar()) == 3.0


def test_out_kwarg_keeps_tape():
    a = nd.array([1.0, 2.0])
    a.attach_grad()
    o = nd.zeros((2,))
    with autograd.record():
        y = nd.exp(a, out=o)
        (y * 1).sum().backward()
    np.testing.assert_allclose(a.grad.asnumpy(), np.exp([1.0, 2.0]),
                               rtol=1e-5)


def test_gamma_negative_sign():
    g = float(nd.gamma(nd.array([-0.5], dtype=np.float64)).asscalar())
    assert abs(g - math.gamma(-0.5)) < 1e-5
