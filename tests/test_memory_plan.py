"""Memory as a managed budget (r10): the pre-dispatch planner must
predict what the memwatch ledger actually measures, the auto-remat
policy must climb the tier ladder only when the budget forces it, host
offload must be numerically invisible, and an OOM must come back with
the cheapest fix that fits — not just a stack trace."""
import gc
import json
import os
import subprocess
import sys

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, memory, nd, sanitizer
from mxnet_tpu.memory import offload, planner, policy
from mxnet_tpu.telemetry import memwatch

REPO = os.path.join(os.path.dirname(__file__), "..")


@pytest.fixture(autouse=True)
def _isolate_memory_state():
    yield
    planner.set_budget(None)
    policy.reset()
    offload.reset()
    memwatch.disable()


# -- planner accuracy ---------------------------------------------------------

def _params_dominated_lane(optimizer, opt_kwargs):
    """Train a params-dominated MLP under the memwatch ledger and return
    (plan, measured_live_bytes).  The ledger tracks live NDArray buffers
    (params / grads / optimizer state / batch), not XLA temps — so the
    lane keeps the batch tiny and the weights fat, and the planner's
    coarse activation prior is noise against the parameter mass."""
    hidden, layers, batch = 1024, 4, 4
    memwatch.enable()
    mx.random.seed(11)
    net = gluon.nn.HybridSequential()
    with net.name_scope():
        for _ in range(layers):
            net.add(gluon.nn.Dense(hidden, activation="relu"))
    net.initialize(mx.init.Xavier())
    net(nd.ones((1, hidden)))
    net.hybridize(static_alloc=True)
    trainer = gluon.Trainer(net.collect_params(), optimizer, opt_kwargs)
    x = mx.random.uniform(shape=(batch, hidden))
    y = mx.random.uniform(shape=(batch, hidden))
    loss_fn = gluon.loss.L2Loss()
    for _ in range(2):
        with autograd.record():
            loss = loss_fn(net(x), y)
        loss.backward()
        trainer.step(batch)
    nd.waitall()
    del loss
    gc.collect()
    live = memwatch.live_bytes()
    plan = planner.plan_model(
        net.collect_params(), optimizer=trainer._optimizer,
        batch_bytes=2 * batch * hidden * 4, remat="none",
        use_registry=False)
    return plan, live


@pytest.mark.parametrize("optimizer,opt_kwargs,n_state", [
    ("sgd", {"learning_rate": 0.01, "momentum": 0.9}, 1),
    ("adam", {"learning_rate": 1e-3}, 2),
])
def test_planner_within_10pct_of_memwatch(optimizer, opt_kwargs, n_state):
    plan, live = _params_dominated_lane(optimizer, opt_kwargs)
    assert plan.fits  # a 50 MB net on a 16 GiB CPU budget
    # the optimizer-state multiplier must be priced per slot
    assert plan.breakdown["optimizer_state"] == \
        n_state * plan.breakdown["params"]
    err = abs(plan.predicted_peak_bytes - live) / live
    assert err <= 0.10, (
        f"planner {plan.predicted_peak_bytes} vs memwatch {live} "
        f"({err:.1%} off)\nbreakdown: {plan.breakdown}")


def test_plan_names_top_buffers_and_records():
    plan, _ = _params_dominated_lane("sgd", {"learning_rate": 0.01,
                                             "momentum": 0.9})
    assert planner.last_plan() is plan
    # the verdict names the offenders: fat Dense weights first
    assert plan.top_buffers[0]["bytes"] >= plan.top_buffers[-1]["bytes"]
    assert any("weight" in b["name"] for b in plan.top_buffers)
    fields = memory.telemetry_fields()
    assert fields["predicted_peak_bytes"] == plan.predicted_peak_bytes


# -- auto-remat tier ladder ---------------------------------------------------

def test_auto_tier_headroom_stays_on_none_budget_escalates():
    params = {"w": ((256, 256), np.float32)}
    mb = 2 ** 20
    hint = 10 * mb  # measured tier-"none" activations
    kw = dict(batch_bytes=1024, activation_hint=hint)

    # CPU default budget (16 GiB): plenty of headroom → cheapest tier,
    # no blanket recompute
    tier, plan = policy.auto_tier(params, **kw)
    assert tier == "none" and plan.fits

    # ~5 MiB budget: "none" (10 MiB of activations) is out, dots
    # (0.35x) squeaks in under the 10% margin
    planner.set_budget(5 * mb)
    tier, plan = policy.auto_tier(params, **kw)
    assert tier == "dots" and plan.fits

    # ~2.5 MiB: only per-layer remat (0.15x) fits
    planner.set_budget(5 * mb // 2)
    tier, plan = policy.auto_tier(params, **kw)
    assert tier == "layer" and plan.fits

    # every decision is recorded for the JSONL remat_policy field
    pol = policy.last_policy()
    assert pol["mode"] == "auto" and pol["tier"] == "layer"
    assert memory.telemetry_fields()["remat_policy"] == "layer"

    # nothing fits: settle on the most frugal tier, carry the bad news
    planner.set_budget(mb // 4)
    tier, plan = policy.auto_tier(params, **kw)
    assert tier == "layer" and not plan.fits


def test_tier_spellings_normalize_and_garbage_raises():
    assert policy.normalize(None) == "none"
    assert policy.normalize(False) == "none"
    assert policy.normalize(True) == "layer"
    assert policy.normalize("full") == "layer"
    assert policy.normalize("dots_saveable") == "dots"
    assert policy.normalize("auto") == "auto"
    with pytest.raises(ValueError):
        policy.normalize("everything")
    with pytest.raises(ValueError):
        policy.checkpoint_wrap(lambda x: x, "auto")  # resolve first


def test_remat_tiers_recompute_but_never_renumber():
    """hybridize(remat=<tier>) must change the backward's memory
    schedule, never the numbers: loss trajectories are BIT-identical
    across the whole ladder."""
    def run(tier):
        mx.random.seed(3)
        net = gluon.nn.HybridSequential()
        with net.name_scope():
            net.add(gluon.nn.Dense(32, activation="relu"))
            net.add(gluon.nn.Dense(32, activation="relu"))
            net.add(gluon.nn.Dense(4))
        net.initialize(mx.init.Xavier())
        net(nd.ones((1, 16)))
        net.hybridize(static_alloc=True, remat=tier)
        trainer = gluon.Trainer(net.collect_params(), "sgd",
                                {"learning_rate": 0.05})
        x = mx.random.uniform(shape=(8, 16))
        y = mx.random.uniform(shape=(8, 4))
        loss_fn = gluon.loss.L2Loss()
        losses = []
        for _ in range(3):
            with autograd.record():
                loss = loss_fn(net(x), y)
            loss.backward()
            trainer.step(8)
            losses.append(float(loss.mean().asscalar()))
        return losses

    ref = run("none")
    assert run("dots") == ref
    assert run("layer") == ref
    # a forced concrete tier is recorded too (mode="forced")
    pol = policy.last_policy()
    assert pol == {"tier": "layer", "mode": "forced",
                   "predicted_peak_bytes": None}


# -- host-offloaded optimizer state -------------------------------------------

def _bf16_net():
    mx.random.seed(0)
    net = gluon.nn.Dense(8)
    net.initialize(mx.init.Xavier())
    net.cast("bfloat16")
    net(nd.ones((4, 6), dtype="bfloat16"))
    return net


def _bf16_step(net, trainer, seed):
    rs = np.random.RandomState(seed)
    x = nd.array(rs.randn(4, 6).astype(np.float32)).astype("bfloat16")
    with autograd.record():
        loss = (net(x) ** 2).mean()
    loss.backward()
    trainer.step(4)


_MP_SGD = {"learning_rate": 0.1, "momentum": 0.9, "multi_precision": True}


def test_offload_host_matches_on_device_oracle_fused():
    """Trainer(offload="host") keeps momentum + f32 masters host-
    resident between steps; the weight trajectory must match the
    on-device oracle per step — the donation contract moves to the
    transient device copies, under the sanitizer's eye."""
    offload.reset()
    sanitizer.enable()
    try:
        oracle = _bf16_net()
        tr_o = gluon.Trainer(oracle.collect_params(), "sgd", _MP_SGD)
        offed = _bf16_net()
        tr_f = gluon.Trainer(offed.collect_params(), "sgd", _MP_SGD,
                             offload="host")
        for s in range(5):
            _bf16_step(oracle, tr_o, s)
            _bf16_step(offed, tr_f, s)
            # state is stashed back to host after every commit
            assert offload.resident_bytes() > 0
            np.testing.assert_allclose(
                offed.weight.data().astype("float32").asnumpy(),
                oracle.weight.data().astype("float32").asnumpy(),
                rtol=1e-5)
        st = offload.stats()
        # state still parked on host after the last commit, and real
        # per-step traffic was booked in both directions
        assert st["resident_bytes"] > 0
        assert st["h2d_bytes_total"] > 0 and st["d2h_bytes_total"] > 0
        assert memory.telemetry_fields()["offload_bytes"] == \
            st["resident_bytes"]
    finally:
        sanitizer.disable()


def test_offload_host_matches_oracle_eager_fallback():
    """Same parity on the eager per-parameter update path (optimizers
    without a fused rule fall back to it)."""
    offload.reset()
    sanitizer.enable()
    try:
        oracle = _bf16_net()
        tr_o = gluon.Trainer(oracle.collect_params(), "sgd", _MP_SGD)
        offed = _bf16_net()
        tr_e = gluon.Trainer(offed.collect_params(), "sgd", _MP_SGD,
                             offload="host")
        tr_e._try_fused_update = lambda: False
        for s in range(3):
            _bf16_step(oracle, tr_o, s)
            _bf16_step(offed, tr_e, s)
            np.testing.assert_allclose(
                offed.weight.data().astype("float32").asnumpy(),
                oracle.weight.data().astype("float32").asnumpy(),
                rtol=1e-5)
        assert offload.resident_bytes() > 0
    finally:
        sanitizer.disable()


def test_offload_rejects_unknown_target():
    from mxnet_tpu.base import MXNetError

    net = _bf16_net()
    with pytest.raises(MXNetError):
        gluon.Trainer(net.collect_params(), "sgd", _MP_SGD,
                      offload="nvme")


# -- OOM prescription ---------------------------------------------------------

def test_oom_comes_back_with_cheapest_fix(tmp_path):
    """An allocation failure must name the cheapest re-planned fix
    (here: remat="layer") in the raised OOMError AND in the post-mortem
    report — the r10 upgrade over round 5's ranked-buffers-only dump."""
    report = tmp_path / "post.json"
    memwatch.enable(report_path=str(report))
    mb = 2 ** 20
    planner.set_budget(4 * mb)
    # 1 MiB params + 1 MiB grads + 1 MiB momentum + 4 MiB activations
    # at tier "none" — over budget; per-layer remat (0.6 MiB) fits
    plan = planner.plan_model(
        {"w": ((512, 512), np.float32)}, optimizer="sgd",
        batch_bytes=0, remat="none", activation_hint=4 * mb,
        use_registry=False)
    assert not plan.fits
    err = RuntimeError("RESOURCE_EXHAUSTED: out of memory while trying "
                       "to allocate 4194304 bytes")
    with pytest.raises(memwatch.OOMError) as ei:
        memwatch.annotate_oom(err, context="test dispatch")
    msg = str(ei.value)
    assert "cheapest fix that fits" in msg
    assert 'remat="layer"' in msg
    rx = json.loads(report.read_text())["prescription"]
    assert rx["recommendation"]["change"] == 'remat="layer"'
    assert rx["recommendation"]["fits"]
    # the ladder was priced in cost-of-fix order, offload included
    changes = [c["change"] for c in rx["candidates"]]
    assert 'offload="host"' in changes and "halve the batch" in changes


# -- offline artifacts: the Mixtral story -------------------------------------

def test_plan_from_artifact_rejects_mixtral_dp2_accepts_dp1():
    """The planner's cold path reads the committed r05 TPU lowerings:
    dp2xep8xtp4 is rejected pre-compile at XLA's own 16.09 GiB figure,
    dp1xep8xtp8 accepted at 11.63 GiB — no topology client needed."""
    budget = int(15.75 * 2 ** 30)
    dp2 = planner.plan_from_artifact(
        os.path.join(REPO, "MIXTRAL_DP2_OVERFLOW_r05.json"))
    assert not dp2.fits
    assert dp2.budget_bytes == budget
    assert dp2.predicted_peak_bytes == 17276874752
    assert dp2.breakdown["arguments"] == 10870120448
    assert dp2.breakdown["temp"] == 6406754304

    dp1 = planner.plan_from_artifact(
        os.path.join(REPO, "MIXTRAL_LOWER_TPU_r05.json"))
    assert dp1.fits
    assert dp1.budget_bytes == budget
    assert dp1.predicted_peak_bytes == 12490305024
    assert round(dp1.predicted_peak_bytes / 2 ** 30, 2) == 11.63
    assert dp1.headroom_bytes > 4 * 2 ** 30


def test_artifact_without_memory_analysis_raises():
    with pytest.raises(ValueError):
        planner.plan_from_artifact({"backend": "tpu"})


def test_mixtral_plan_tool_emits_artifact(tmp_path):
    """tools/mixtral_plan.py end to end: the committed-artifact lane
    reproduces the TPU verdicts exactly, the analytic lane agrees on
    both meshes, and the recommendation is the confirmed dp1xep8xtp8
    recipe."""
    out = tmp_path / "mixtral_plan.json"
    env = dict(os.environ)
    env.update(JAX_PLATFORMS="cpu", MXT_MIXTRAL_PLAN_OUT=str(out))
    env.pop("XLA_FLAGS", None)
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "mixtral_plan.py")],
        capture_output=True, text=True, env=env, cwd=REPO, timeout=300)
    assert r.returncode == 0, r.stderr[-2000:]
    rec = json.loads(out.read_text())
    assert rec["n_params"] == 46702792704
    assert not rec["lanes"]["dp2xep8xtp4"]["artifact_plan"]["fits"]
    assert rec["lanes"]["dp1xep8xtp8"]["artifact_plan"]["fits"]
    assert rec["recommendation"]["confirmed_by"] == \
        "MIXTRAL_LOWER_TPU_r05.json"
    assert all(rec["acceptance"].values())


def test_plan_kv_pool_math_and_tp_division():
    """The serving KV pool planner: 2 (K and V) × layers × block-pool
    bytes, divided by tp when the ``llama_serving`` rules shard the
    pool's head axis — and it predicts the live engine's figure."""
    import jax
    from jax.sharding import Mesh

    # llama_tiny geometry: 2 layers, 2 kv heads, head_dim 16
    b = planner.plan_kv_pool(2, 2, 16, num_blocks=8, block_size=16)
    assert b == 2 * 2 * (8 * 2 * 16 * 16 * 4)      # 65536, replicated
    if len(jax.devices()) >= 2:
        tp2 = Mesh(np.array(jax.devices()[:2]), ("tp",))
        assert planner.plan_kv_pool(2, 2, 16, num_blocks=8,
                                    block_size=16, mesh=tp2) == b // 2
    # plan_model folds it into the breakdown and the peak
    from mxnet_tpu.models.llama import llama_tiny

    net = llama_tiny()
    net.initialize()
    base = memory.plan_model(net, training=False)
    plan = memory.plan_model(net, training=False, kv_pool_bytes=b)
    assert plan.breakdown["kv_pool"] == b
    assert plan.predicted_peak_bytes == base.predicted_peak_bytes + b
    assert any(t["name"] == "<kv_pool>" for t in plan.top_buffers)
