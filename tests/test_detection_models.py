"""Detection model zoo tests (reference model: GluonCV model unit tests —
forward shape checks in train + inference modes, hybridized and not).
Small input sizes keep CPU-mesh compile times down."""
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd, nd
from mxnet_tpu.gluon.model_zoo import detection


def _init(net):
    net.initialize(mx.init.Xavier())
    return net


def test_ssd_train_and_infer_shapes():
    net = _init(detection.ssd_300_resnet18_v1(classes=4))
    x = nd.random.uniform(shape=(2, 3, 96, 96))
    with autograd.record():
        cls_p, box_p, anchors = net(x)
    n = anchors.shape[1]
    assert cls_p.shape == (2, n, 5)
    assert box_p.shape == (2, n, 4)
    assert anchors.shape == (1, n, 4)
    ids, scores, bboxes = net(x)
    assert ids.shape[0] == 2 and ids.shape[2] == 1
    assert bboxes.shape[2] == 4


def test_ssd_end_to_end_loss_step():
    from mxnet_tpu import gluon
    net = _init(detection.ssd_300_resnet18_v1(classes=2))
    x = nd.random.uniform(shape=(1, 3, 96, 96))
    label = nd.array([[[0.0, 0.2, 0.2, 0.7, 0.7]]])
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.01})
    with autograd.record():
        cls_p, box_p, anchors = net(x)
        loc_t, loc_m, cls_t = nd.contrib.MultiBoxTarget(
            anchors, label, nd.transpose(cls_p, axes=(0, 2, 1)))
        cls_loss = nd.softmax_cross_entropy(
            cls_p.reshape((-1, cls_p.shape[-1])), cls_t.reshape((-1,)))
        loc_loss = (nd.abs(box_p.reshape((1, -1)) - loc_t) * loc_m).sum()
        loss = cls_loss.sum() + loc_loss
    loss.backward()
    trainer.step(1)
    g = list(net.collect_params().values())[0].grad()
    assert np.isfinite(loss.asscalar())
    assert np.all(np.isfinite(g.asnumpy()))


def test_darknet53_classifier():
    net = _init(detection.darknet53(classes=10))
    out = net(nd.random.uniform(shape=(2, 3, 64, 64)))
    assert out.shape == (2, 10)


def test_yolo3_train_and_infer():
    net = _init(detection.yolo3_darknet53(classes=3))
    x = nd.random.uniform(shape=(1, 3, 64, 64))
    with autograd.record():
        preds, boxes, scores = net(x)
    n = preds.shape[1]
    assert preds.shape == (1, n, 8)  # 5 + 3 classes
    assert boxes.shape == (1, n, 4)
    assert scores.shape == (1, n, 3)
    # anchors cover /8 /16 /32 scales: 64px → 8²+4²+2² cells × 3 anchors
    assert n == (64 + 16 + 4) * 3
    ids, sc, bb = net(x)
    assert ids.shape[2] == 1 and bb.shape[2] == 4
    # decoded inference boxes are pixel-space within a loose image bound
    kept = sc.asnumpy() > 0
    assert np.isfinite(bb.asnumpy()).all()


def test_yolo3_hybridize_consistent():
    net = _init(detection.yolo3_darknet53(classes=3))
    x = nd.random.uniform(shape=(1, 3, 64, 64))
    eager = net(x)
    net.hybridize()
    hybrid = net(x)
    for e, h in zip(eager, hybrid):
        np.testing.assert_allclose(e.asnumpy(), h.asnumpy(), rtol=1e-4,
                                   atol=1e-5)


def test_faster_rcnn_train_and_infer():
    net = _init(detection.faster_rcnn_resnet50_v1(classes=3,
                                                  rpn_post_nms=8))
    x = nd.random.uniform(shape=(1, 3, 96, 96))
    with autograd.record():
        rois, cls_pred, box_pred, rpn_s, rpn_l = net(x)
    assert rois.shape == (8, 5)
    assert cls_pred.shape == (8, 4)  # 3 classes + bg
    assert box_pred.shape == (8, 4)
    ids, sc, bb = net(x)
    assert ids.shape == (1, 8, 1)
    assert bb.shape == (1, 8, 4)


def test_detection_get_model():
    net = detection.get_model("ssd_300_resnet18_v1", classes=2)
    assert isinstance(net, detection.SSD)
