"""Donation-sanitizer tests (``MXNET_SANITIZE_DONATION=1``): stale
views of buffers donated by the fused trainer update, the K-step fused
program, and the per-param optimizer update must raise a precise
use-after-donation error naming the donating site; rebinding through
the owner clears the poison; disabled, the hooks must stay within
noise of a stub (telemetry-style null-path bound)."""
import os
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import autograd, gluon, nd, sanitizer  # noqa: E402
from mxnet_tpu.sanitizer import DonatedBufferError  # noqa: E402


@pytest.fixture
def san():
    """Enable the sanitizer for one test, restore the ambient state."""
    was = sanitizer.is_enabled()
    sanitizer.enable()
    sanitizer.reset()
    yield sanitizer
    if not was:
        sanitizer.disable()


def _mlp():
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(16, activation="relu"), gluon.nn.Dense(4))
    net.initialize(mx.init.Xavier())
    return net


def _backward(net, loss_fn, x, y):
    with autograd.record():
        loss = loss_fn(net(x), y)
    loss.backward()


def _data(batch=8, dim=6, classes=4, seed=0):
    rng = np.random.RandomState(seed)
    return (nd.array(rng.randn(batch, dim).astype(np.float32)),
            nd.array(rng.randint(0, classes, (batch,))))


# --- trainer fused multi-tensor update --------------------------------------

def test_stale_view_after_fused_trainer_step_raises(san):
    net = _mlp()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 1e-3})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    x, y = _data()
    _backward(net, loss_fn, x, y)

    param = next(iter(net.collect_params().values()))
    stale = param.data().detach()  # shares the pre-step raw buffer
    trainer.step(8)

    with pytest.raises(DonatedBufferError) as ei:
        stale.asnumpy()
    msg = str(ei.value)
    assert "used after donation" in msg
    assert "Trainer._try_fused_update" in msg
    assert "donate_argnums" in msg


def test_stale_view_poisons_op_dispatch_too(san):
    net = _mlp()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 1e-3})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    x, y = _data()
    _backward(net, loss_fn, x, y)
    stale = next(iter(net.collect_params().values())).data().detach()
    trainer.step(8)
    with pytest.raises(DonatedBufferError, match="operand"):
        _ = stale + 1


def test_rebind_clears_poison_and_donated_property(san):
    net = _mlp()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 1e-3})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    x, y = _data()
    _backward(net, loss_fn, x, y)

    param = next(iter(net.collect_params().values()))
    stale = param.data().detach()
    trainer.step(8)

    # the stale alias is poisoned and says where the buffer died ...
    assert stale._donated is not None
    assert "Trainer._try_fused_update" in stale._donated
    # ... but the live holder was rebound to the result buffer: clean
    fresh = param.data()
    assert fresh._donated is None
    assert np.isfinite(fresh.asnumpy()).all()

    # the cleared handle survives further training untouched
    _backward(net, loss_fn, x, y)
    trainer.step(8)
    assert param.data()._donated is None


# --- K-step fused program (FusedTrainStep) ----------------------------------

def test_stale_view_after_fused_train_step_raises(san):
    net = _mlp()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.05})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    x, y = _data()
    _backward(net, loss_fn, x, y)  # materialize grads/states for fusing
    trainer.step(8)

    step = gluon.FusedTrainStep(
        net, trainer, lambda n, a, b: loss_fn(n(a), b),
        steps_per_execution=2, batch_size=8, stacked_inputs=False)
    param = next(iter(net.collect_params().values()))
    stale = param.data().detach()
    step(x, y)

    with pytest.raises(DonatedBufferError) as ei:
        stale.wait_to_read()
    assert "FusedTrainStep.__call__" in str(ei.value)
    # the live weights read fine after the K-step commit
    assert np.isfinite(param.data().asnumpy()).all()


# --- per-param optimizer update ---------------------------------------------

def test_stale_view_after_per_param_update_raises(san):
    opt = mx.optimizer.create("adam", learning_rate=1e-3)
    weight = nd.array(np.random.RandomState(1).randn(8, 4)
                      .astype(np.float32))
    grad = nd.array(np.random.RandomState(2).randn(8, 4)
                    .astype(np.float32))
    state = opt.create_state(0, weight)
    stale = weight.detach()

    opt.update(0, weight, grad, state)

    with pytest.raises(DonatedBufferError) as ei:
        stale.asnumpy()
    assert "Optimizer._update_impl" in str(ei.value)
    # the weight holder itself was rebound to the fresh result
    assert weight._donated is None
    assert np.isfinite(weight.asnumpy()).all()


# --- env-var wiring ---------------------------------------------------------

def test_env_var_enables_sanitizer_in_subprocess():
    code = """
import numpy as np
import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd, sanitizer

assert sanitizer.is_enabled(), "MXNET_SANITIZE_DONATION=1 must autostart"
net = gluon.nn.HybridSequential()
net.add(gluon.nn.Dense(8), gluon.nn.Dense(4))
net.initialize(mx.init.Xavier())
trainer = gluon.Trainer(net.collect_params(), "adam",
                        {"learning_rate": 1e-3})
loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
x = nd.array(np.random.randn(4, 6).astype(np.float32))
y = nd.array(np.random.randint(0, 4, (4,)))
with autograd.record():
    loss = loss_fn(net(x), y)
loss.backward()
stale = next(iter(net.collect_params().values())).data().detach()
trainer.step(4)
try:
    stale.asnumpy()
except sanitizer.DonatedBufferError as e:
    assert "used after donation" in str(e)
    print("SANITIZER_OK")
"""
    env = dict(os.environ)
    env["MXNET_SANITIZE_DONATION"] = "1"
    env["JAX_PLATFORMS"] = "cpu"
    r = subprocess.run([sys.executable, "-c", code], capture_output=True,
                       text=True, env=env, cwd=REPO, timeout=600)
    assert r.returncode == 0, r.stderr[-2000:]
    assert "SANITIZER_OK" in r.stdout


def test_disabled_by_default_and_registry_empty():
    # the ambient test process runs without MXNET_SANITIZE_DONATION:
    # hooks must not record anything and _donated must read None
    if sanitizer.is_enabled():
        pytest.skip("suite running with sanitizer force-enabled")
    x = nd.array([1.0, 2.0])
    assert x._donated is None
    assert sanitizer.site_of(x._data) is None


# --- disabled-mode overhead --------------------------------------------------

def test_sanitizer_disabled_step_overhead():
    """Same null-path bound as telemetry: the shipped step loop (hooks
    present, sanitizer off) must stay within a generous ratio of the
    loop with every sanitizer entry point stubbed to a no-op — catches
    a registry lookup or lock sneaking onto the disabled path."""
    import time

    from mxnet_tpu import sanitizer as san

    assert not san.is_enabled()
    net = _mlp()
    net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.05})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    x, y = _data()

    def steps(n):
        for _ in range(n):
            _backward(net, loss_fn, x, y)
            trainer.step(8)
        next(iter(net.collect_params().values())).data().wait_to_read()

    def best_of(repeats, n):
        best = float("inf")
        for _ in range(repeats):
            t0 = time.perf_counter()
            steps(n)
            best = min(best, time.perf_counter() - t0)
        return best

    steps(3)  # trace+compile outside the timed region
    hooked = best_of(3, 20)

    noop = lambda *a, **k: None  # noqa: E731
    saved = {name: getattr(san, name)
             for name in ("donate", "check", "site_of")}
    try:
        for name in saved:
            setattr(san, name, noop)
        steps(3)
        stubbed = best_of(3, 20)
    finally:
        for name, fn in saved.items():
            setattr(san, name, fn)

    assert hooked < stubbed * 3 + 0.01, (hooked, stubbed)
