"""NDArray op tests (reference model: tests/python/unittest/test_ndarray.py
and test_operator.py — numpy cross-checks + finite-difference gradients)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.test_utils import (assert_almost_equal,
                                  check_numeric_gradient, rand_ndarray)


def test_creation():
    a = nd.zeros((2, 3))
    assert a.shape == (2, 3) and a.dtype == np.float32
    b = nd.ones((4,), dtype="int32")
    assert b.dtype == np.int32
    c = nd.array([[1, 2], [3, 4]])
    assert c.dtype == np.float32  # python payloads default to f32
    assert_almost_equal(c, np.array([[1, 2], [3, 4]]))
    d = nd.full((2, 2), 7.0)
    assert float(d[0, 0].asscalar()) == 7.0
    e = nd.arange(0, 10, 2)
    assert_almost_equal(e, np.arange(0, 10, 2, dtype=np.float32))


def test_arithmetic_broadcast():
    a = rand_ndarray((3, 4))
    b = rand_ndarray((1, 4))
    for op in ["+", "-", "*", "/"]:
        got = eval(f"a {op} b").asnumpy()
        want = eval(f"a.asnumpy() {op} b.asnumpy()")
        assert_almost_equal(got, want)
    assert_almost_equal((a + 1.5), a.asnumpy() + 1.5)
    assert_almost_equal((2.0 - a), 2.0 - a.asnumpy())
    assert_almost_equal((a ** 2), a.asnumpy() ** 2)


def test_inplace():
    a = nd.ones((2, 2))
    a += 2
    assert_almost_equal(a, np.full((2, 2), 3.0))
    a *= 2
    assert_almost_equal(a, np.full((2, 2), 6.0))
    a[:] = 1.0
    assert_almost_equal(a, np.ones((2, 2)))


def test_indexing():
    a = nd.array(np.arange(24).reshape(2, 3, 4))
    assert_almost_equal(a[1], np.arange(24).reshape(2, 3, 4)[1])
    assert_almost_equal(a[0, 1:3], np.arange(24).reshape(2, 3, 4)[0, 1:3])
    a[0, 0, 0] = 100.0
    assert a[0, 0, 0].asscalar() == 100.0
    idx = nd.array([0, 1], dtype="int32")
    taken = nd.take(a, idx, axis=0)
    assert taken.shape == (2, 3, 4)


def test_reshape_family():
    a = nd.array(np.arange(24).reshape(2, 3, 4))
    assert nd.reshape(a, shape=(0, -1)).shape == (2, 12)
    assert a.reshape((4, 6)).shape == (4, 6)
    assert nd.flatten(a).shape == (2, 12)
    assert nd.transpose(a).shape == (4, 3, 2)
    assert nd.expand_dims(a, axis=1).shape == (2, 1, 3, 4)
    assert nd.squeeze(nd.expand_dims(a, axis=0), axis=0).shape == (2, 3, 4)
    assert nd.swapaxes(a, 0, 2).shape == (4, 3, 2)
    assert nd.tile(a, (2, 1, 1)).shape == (4, 3, 4)
    assert nd.slice_axis(a, axis=2, begin=1, end=3).shape == (2, 3, 2)
    assert nd.slice(a, begin=(0, 1), end=(2, 3)).shape == (2, 2, 4)


def test_concat_stack_split():
    a, b = nd.ones((2, 3)), nd.zeros((2, 3))
    assert nd.concat(a, b, dim=0).shape == (4, 3)
    assert nd.concat(a, b, dim=1).shape == (2, 6)
    assert nd.stack(a, b, axis=0).shape == (2, 2, 3)
    parts = nd.split(nd.ones((4, 6)), num_outputs=3, axis=1)
    assert len(parts) == 3 and parts[0].shape == (4, 2)


def test_reductions():
    x = rand_ndarray((3, 4, 5))
    xn = x.asnumpy()
    assert_almost_equal(nd.sum(x), xn.sum())
    assert_almost_equal(nd.sum(x, axis=1), xn.sum(1))
    assert_almost_equal(nd.mean(x, axis=(0, 2)), xn.mean((0, 2)))
    assert_almost_equal(nd.max(x, axis=1, keepdims=True),
                        xn.max(1, keepdims=True))
    assert_almost_equal(nd.norm(x), np.sqrt((xn ** 2).sum()))
    assert_almost_equal(nd.sum(x, axis=1, exclude=True), xn.sum((0, 2)))


def test_dot():
    a = rand_ndarray((3, 4))
    b = rand_ndarray((4, 5))
    assert_almost_equal(nd.dot(a, b), a.asnumpy() @ b.asnumpy())
    assert_almost_equal(nd.dot(a, b.T, transpose_b=True),
                        a.asnumpy() @ b.asnumpy())
    c = rand_ndarray((2, 3, 4))
    d = rand_ndarray((2, 4, 5))
    assert_almost_equal(nd.batch_dot(c, d),
                        np.matmul(c.asnumpy(), d.asnumpy()))


def test_ordering():
    x = nd.array([[3.0, 1.0, 2.0], [0.5, 2.5, 1.5]])
    assert_almost_equal(nd.sort(x, axis=1), np.sort(x.asnumpy(), 1))
    assert_almost_equal(nd.argsort(x, axis=1),
                        np.argsort(x.asnumpy(), 1).astype(np.float32))
    v = nd.topk(x, k=2, ret_typ="value")
    assert_almost_equal(v, np.array([[3.0, 2.0], [2.5, 1.5]]))
    assert_almost_equal(nd.argmax(x, axis=1), np.array([0.0, 1.0]))


def test_one_hot_pick_gather():
    idx = nd.array([0, 2], dtype="int32")
    oh = nd.one_hot(idx, 3)
    assert_almost_equal(oh, np.eye(3, dtype=np.float32)[[0, 2]])
    x = nd.array([[1.0, 2.0, 3.0], [4.0, 5.0, 6.0]])
    assert_almost_equal(nd.pick(x, nd.array([1, 2])), np.array([2.0, 6.0]))
    data = nd.array(np.arange(9).reshape(3, 3))
    indices = nd.array([[0, 1], [1, 2]])
    assert_almost_equal(nd.gather_nd(data, indices), np.array([1.0, 5.0]))


def test_where_clip():
    c = nd.array([1.0, 0.0, 1.0])
    x, y = nd.ones((3,)), nd.zeros((3,))
    assert_almost_equal(nd.where(c, x, y), np.array([1.0, 0.0, 1.0]))
    assert_almost_equal(nd.clip(nd.array([-2.0, 0.5, 9.0]), 0.0, 1.0),
                        np.array([0.0, 0.5, 1.0]))


def test_unary_ops_numpy_parity():
    x = rand_ndarray((3, 3), scale=0.9)
    xn = x.asnumpy()
    for name, ref in [("exp", np.exp), ("log1p", np.log1p),
                      ("sqrt", lambda v: np.sqrt(np.abs(v))),
                      ("abs", np.abs), ("tanh", np.tanh),
                      ("floor", np.floor), ("ceil", np.ceil),
                      ("square", np.square), ("sign", np.sign)]:
        arg = nd.abs(x) if name == "sqrt" else x
        argn = np.abs(xn) if name == "sqrt" else xn
        assert_almost_equal(getattr(nd, name)(arg), ref(argn), names=(name,) * 2)


def test_comparison_dtype():
    a = nd.array([1.0, 2.0])
    b = nd.array([2.0, 2.0])
    eq = (a == b)
    assert eq.dtype == np.float32  # MXNet returns 0/1 floats, not bools
    assert_almost_equal(eq, np.array([0.0, 1.0]))


def test_context_and_copy():
    a = nd.ones((2, 2), ctx=mx.cpu())
    assert a.context == mx.cpu(0)
    b = a.copyto(nd.zeros((2, 2)))
    assert_almost_equal(b, np.ones((2, 2)))
    c = a.as_in_context(mx.cpu(0))
    assert c.context == mx.cpu(0)
    d = a.astype("float16")
    assert d.dtype == np.float16


def test_save_load(tmp_path):
    f = str(tmp_path / "arrs.npz")
    nd.save(f, {"w": nd.ones((2, 2)), "b": nd.zeros((3,))})
    loaded = nd.load(f)
    assert set(loaded) == {"w", "b"}
    assert_almost_equal(loaded["w"], np.ones((2, 2)))


def test_wait_and_async():
    a = nd.ones((64, 64))
    b = nd.dot(a, a)
    b.wait_to_read()  # engine WaitForVar analog
    nd.waitall()
    assert_almost_equal(b[0, 0], np.array(64.0))


def test_grad_elemwise():
    check_numeric_gradient(lambda x: nd.tanh(x) * x,
                           [np.random.rand(3, 3) - 0.5])


def test_grad_dot():
    check_numeric_gradient(
        lambda a, b: nd.dot(a, b),
        [np.random.rand(3, 4), np.random.rand(4, 2)])


def test_grad_reduce_broadcast():
    check_numeric_gradient(
        lambda x: nd.sum(x * 2.0, axis=1) ** 2,
        [np.random.rand(3, 4)])


def test_grad_softmax():
    w = nd.array(np.random.rand(2, 5), dtype=np.float64)
    check_numeric_gradient(
        lambda x: nd.softmax(x, axis=-1) * w,
        [np.random.rand(2, 5)], rtol=2e-2, atol=2e-3)


def test_sequence_mask():
    x = nd.ones((4, 2, 3))
    sl = nd.array([2, 4])
    m = nd.SequenceMask(x, sl, use_sequence_length=True, value=0.0)
    mn = m.asnumpy()
    assert mn[:2, 0].sum() == 6.0 and mn[2:, 0].sum() == 0.0
    assert mn[:, 1].sum() == 12.0


def test_random_ops():
    mx.random.seed(0)
    u = mx.random.uniform(0, 1, shape=(100,))
    assert 0.0 <= float(u.min().asscalar()) and float(u.max().asscalar()) <= 1.0
    n1 = mx.random.normal(0, 1, shape=(5,)).asnumpy()
    mx.random.seed(0)
    _ = mx.random.uniform(0, 1, shape=(100,))
    n2 = mx.random.normal(0, 1, shape=(5,)).asnumpy()
    np.testing.assert_allclose(n1, n2)  # seeded reproducibility
    r = mx.random.randint(0, 10, shape=(50,))
    assert r.dtype == np.int32
