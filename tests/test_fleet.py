"""Fleet-wide training observability (ISSUE 13): rank-aware step
records, the stride-gated fleet exchange, the straggler/anomaly
watchdog, the training flight recorder, and the fleet report CLI.

Coverage map:
  * watchdog math as pure functions (skew / NaN / spike / regression,
    K-consecutive-window streaks);
  * in-process single-rank behavior: rank/world stamping, fleet views
    at the stride, anomaly records + counters + callback/halt, ring
    bounds, rate-limited dumps, /metrics == telemetry counters;
  * disabled-path guards (fleet off = one boolean check; PR 2/12
    pattern);
  * read_jsonl multi-path/glob merge by (step, rank);
  * SIGTERM-drain dump roundtrip through tools/fleet_report.py;
  * the dp2 CPU-mesh chaos lane: a SLOW_RANK-hooked straggler must be
    NAMED in the fleet view, the anomaly stream and the report CLI,
    and a SIGKILL'd rank must leave a readable flight dump.
"""
import json
import math
import os
import signal
import socket
import subprocess
import sys
import time
import urllib.request

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import checkpoint, gluon, nd, telemetry
from mxnet_tpu.gluon import trainer as trainer_mod
from mxnet_tpu.telemetry import fleet
from mxnet_tpu.telemetry.sinks import ListSink, read_jsonl

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
WORKER = os.path.join(REPO, "tests", "_preempt_worker.py")


def _fleet_report():
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import fleet_report
    return fleet_report


@pytest.fixture(autouse=True)
def _clean_fleet():
    telemetry.disable()
    telemetry.reset()
    fleet.clear()
    yield
    telemetry.disable()
    telemetry.reset()
    fleet.clear()


# --- watchdog math (pure functions) -----------------------------------------

def test_detect_skew_names_outlier_ranks():
    assert fleet.detect_skew([10.0, 10.0, 25.0, 10.0], 1.5) == [2]
    assert fleet.detect_skew([10.0, 10.0, 10.0], 1.5) == []
    assert fleet.detect_skew([10.0, 50.0], 1.5) == [1]
    # degenerate inputs are quiet, never raising
    assert fleet.detect_skew([7.0], 1.5) == []
    assert fleet.detect_skew([], 1.5) == []
    assert fleet.detect_skew([0.0, 0.0, 0.0], 1.5) == []


def test_detect_nan_inf_and_nonnumbers():
    assert fleet.detect_nan(float("nan"))
    assert fleet.detect_nan(float("inf"))
    assert fleet.detect_nan(float("-inf"))
    assert fleet.detect_nan("not-a-number")
    assert not fleet.detect_nan(3.5)
    assert not fleet.detect_nan(0)


def test_detect_spike_respects_min_history():
    hist = [1.0] * 7
    assert not fleet.detect_spike(100.0, hist, factor=10, min_history=8)
    hist.append(1.0)
    assert fleet.detect_spike(100.0, hist, factor=10, min_history=8)
    assert not fleet.detect_spike(5.0, hist, factor=10, min_history=8)
    assert not fleet.detect_spike(100.0, [0.0] * 8, factor=10,
                                  min_history=8)


def test_watchdog_streak_fires_after_k_consecutive_windows():
    wd = fleet.Watchdog(skew_threshold=1.5, consecutive=3)
    skewed = {"compute_ms": [10.0, 50.0],
              "allreduce_wait_ms": [5.0, 5.0]}
    assert wd.observe_fleet(16, skewed) == []
    assert wd.observe_fleet(32, skewed) == []
    out = wd.observe_fleet(48, skewed)
    assert [a["kind"] for a in out] == ["straggler"]
    assert out[0]["culprit"] == 1
    assert out[0]["windows"] == 3
    assert out[0]["ratio"] > 1.5
    # a clean window resets the streak; re-skewing starts from scratch
    clean = {"compute_ms": [10.0, 10.0], "allreduce_wait_ms": [5.0, 5.0]}
    assert wd.observe_fleet(64, clean) == []
    assert wd.observe_fleet(80, skewed) == []


def test_watchdog_flags_allreduce_wait_skew_separately():
    wd = fleet.Watchdog(skew_threshold=1.5, consecutive=1)
    view = {"compute_ms": [10.0, 10.0],
            "allreduce_wait_ms": [50.0, 5.0]}
    out = wd.observe_fleet(16, view)
    assert [a["kind"] for a in out] == ["allreduce_wait_skew"]
    assert out[0]["culprit"] == 0


def test_watchdog_local_detectors():
    wd = fleet.Watchdog(min_history=4, spike_factor=10.0,
                        regression_factor=2.0)
    for _ in range(6):
        assert wd.observe_step({"loss": 1.0, "grad_norm": 1.0,
                                "step_ms": 10.0}) == []
    out = wd.observe_step({"loss": float("nan"), "grad_norm": 50.0,
                           "step_ms": 25.0})
    assert {a["kind"] for a in out} == \
        {"nan_loss", "grad_spike", "step_regression"}


# --- disabled path (PR 2/12 pattern) ----------------------------------------

class _PoisonLock:
    def __enter__(self):
        raise AssertionError("disabled fleet path took a lock")

    def __exit__(self, *exc):
        return False

    acquire = __enter__


def test_fleet_disabled_never_locks_or_mutates(monkeypatch):
    assert not fleet.is_enabled()
    monkeypatch.setattr(fleet, "_lock", _PoisonLock())
    monkeypatch.setattr(fleet, "_ring_lock", _PoisonLock())
    rec = {"step": 1, "step_ms": 5.0, "loss": float("nan")}
    fleet.on_step_record(rec)
    assert "rank" not in rec
    assert fleet.incident("anything") is None


def test_fleet_disabled_overhead_bounded():
    rec = {"step": 1, "step_ms": 5.0}
    t0 = time.perf_counter()
    for _ in range(10_000):
        fleet.on_step_record(rec)
    assert time.perf_counter() - t0 < 0.5


def test_exchange_tolerates_six_column_peers(monkeypatch):
    """Rows gathered from pre-r20 peers carry six floats (no
    duty_cycle); the view renders their duty cycle as 0.0 (unknown)
    instead of crashing or misaligning columns — the same back-compat
    contract the r17 first_nan_layer bump established."""
    import types

    # a fake 2-rank gather that STRIPS the 7th float, as an old peer's
    # packed vector would
    def gather(vec):
        return [list(vec)[:6], list(vec)[:6]]

    fake_pl = types.SimpleNamespace(process_gather_hostvec=gather)
    # patch the indirection point, not sys.modules: injecting a fake
    # mxnet_tpu.parallel would also flip world()'s cache-enable check
    monkeypatch.setattr(fleet, "_parallel", lambda: fake_pl)
    monkeypatch.setattr(fleet, "world", lambda: (0, 2))
    view = fleet._fleet_exchange(
        {"step": 7, "step_ms": 10.0,
         "counters": {"trainer.allreduce_wait_ms": 2.0}})
    assert view["world_size"] == 2
    assert view["duty_cycle"] == [0.0, 0.0]
    assert view["first_nan_layer"] == [-1, -1]
    assert view["compute_ms"] == [8.0, 8.0]


def test_exchange_seven_column_rows_carry_duty_cycle(monkeypatch):
    import types

    def gather(vec):
        return [list(vec), list(vec)]

    fake_pl = types.SimpleNamespace(process_gather_hostvec=gather)
    monkeypatch.setattr(fleet, "_parallel", lambda: fake_pl)
    monkeypatch.setattr(fleet, "world", lambda: (1, 2))
    view = fleet._fleet_exchange(
        {"step": 9, "step_ms": 10.0,
         "counters": {"trainer.allreduce_wait_ms": 2.0}})
    assert view["duty_cycle"] == [pytest.approx(0.8)] * 2


def test_telemetry_on_fleet_off_leaves_records_unstamped():
    telemetry.enable()
    sink = ListSink()
    telemetry.add_sink(sink)
    telemetry.step_begin()
    rec = telemetry.step_end(examples=4)
    assert rec is not None
    assert "rank" not in rec and "world_size" not in rec
    assert all(r.get("record") != "fleet" for r in sink.records)


# --- rank stamping + fleet views at the stride ------------------------------

def test_step_records_gain_rank_and_views_emit_at_stride():
    telemetry.enable()
    fleet.enable(stride=2)
    sink = ListSink()
    telemetry.add_sink(sink)
    for _ in range(5):
        telemetry.step_begin()
        telemetry.count("trainer.allreduce_wait_ms", 2.0)
        telemetry.step_end(examples=8, loss=0.5)
    steps = [r for r in sink.records if r.get("record") is None]
    assert len(steps) == 5
    assert all(r["rank"] == 0 and r["world_size"] == 1 for r in steps)
    views = [r for r in sink.records if r.get("record") == "fleet"]
    assert [v["step"] for v in views] == [2, 4]
    v = views[-1]
    assert v["world_size"] == 1 and v["stride"] == 2
    for col in ("step_ms", "allreduce_wait_ms", "compute_ms",
                "peak_live_bytes", "examples_per_sec", "duty_cycle"):
        assert len(v[col]) == 1, col
    assert v["allreduce_wait_ms"] == [2.0]
    assert v["compute_ms"][0] == pytest.approx(
        max(v["step_ms"][0] - 2.0, 0.0))
    # r20: the 7th exchanged float is compute_ms / step_ms in [0, 1]
    assert v["duty_cycle"][0] == pytest.approx(
        v["compute_ms"][0] / v["step_ms"][0], abs=1e-3)
    assert v["stragglers"] == []
    assert telemetry.counters()["fleet.exchange"] == 2
    assert fleet.last_view()["step"] == 4
    # the flight ring holds step records AND views
    ring = fleet.recent()
    assert sum(1 for r in ring if r.get("record") == "fleet") == 2
    assert sum(1 for r in ring if r.get("record") is None) == 5


# --- anomalies: emission, counters, callback, halt --------------------------

def test_nan_loss_anomaly_emitted_and_counted():
    telemetry.enable()
    fleet.enable(stride=10_000)
    sink = ListSink()
    telemetry.add_sink(sink)
    telemetry.step_begin()
    telemetry.step_end(examples=4, loss=float("nan"))
    anomalies = [r for r in sink.records if r.get("record") == "anomaly"]
    assert len(anomalies) == 1
    a = anomalies[0]
    assert a["kind"] == "nan_loss" and a["rank"] == 0 and a["step"] == 1
    c = telemetry.counters()
    assert c["fleet.anomaly"] == 1
    assert c["fleet.anomaly.nan_loss"] == 1
    assert any(r.get("record") == "anomaly" for r in fleet.recent())


def test_anomaly_callback_replaces_default_warning():
    seen = []
    telemetry.enable()
    fleet.enable(stride=10_000, on_anomaly=seen.append)
    telemetry.step_begin()
    telemetry.step_end(loss=float("inf"))
    assert [a["kind"] for a in seen] == ["nan_loss"]


def test_watchdog_halt_raises_at_step_boundary_and_dumps(tmp_path,
                                                         monkeypatch):
    dump = str(tmp_path / "halt.json")
    monkeypatch.setenv("MXNET_FLEET_DUMP", dump)
    telemetry.enable()
    fleet.enable(stride=10_000, halt=True)
    telemetry.step_begin()
    with pytest.raises(fleet.WatchdogHalt):
        telemetry.step_end(loss=float("nan"))
    assert fleet.halt_requested()
    with open(dump) as f:
        doc = json.load(f)
    assert doc["record"] == "flight_recorder"
    assert doc["kind"] == "fleet"
    assert doc["reason"] == "watchdog_halt"
    assert any(r.get("record") == "anomaly" for r in doc["records"])


# --- flight recorder: ring bounds, dumps, rate limit ------------------------

def test_ring_bounded_and_dump_roundtrip(tmp_path):
    telemetry.enable()
    fleet.enable(stride=10_000, ring=8)
    for _ in range(20):
        telemetry.step_begin()
        telemetry.step_end(examples=4)
    ring = fleet.recent()
    assert len(ring) == 8
    assert [r["step"] for r in ring] == list(range(13, 21))
    assert fleet.recent(3) == ring[-3:]
    path = fleet.dump(str(tmp_path / "d.json"), reason="manual",
                      context={"why": "test"})
    with open(path) as f:
        doc = json.load(f)
    assert doc["rank"] == 0 and doc["world_size"] == 1
    assert doc["context"] == {"why": "test"}
    assert len(doc["records"]) == 8


def test_incident_rate_limited_per_reason(tmp_path):
    telemetry.enable()
    fleet.enable(stride=10_000)
    telemetry.step_begin()
    telemetry.step_end()
    p1 = fleet.incident("restart", path=str(tmp_path / "a.json"))
    p2 = fleet.incident("restart", path=str(tmp_path / "b.json"))
    p3 = fleet.incident("other", path=str(tmp_path / "c.json"))
    assert p1 is not None and os.path.exists(p1)
    assert p2 is None  # throttled: same reason inside DUMP_INTERVAL_S
    assert p3 is not None  # distinct reason has its own limiter


def test_incident_never_raises(monkeypatch):
    telemetry.enable()
    fleet.enable(stride=10_000)

    def boom(*a, **k):
        raise OSError("disk gone")

    monkeypatch.setattr(fleet, "dump", boom)
    assert fleet.incident("restart") is None


def test_oom_postmortem_embeds_recent_steps(tmp_path):
    telemetry.enable()
    fleet.enable(stride=10_000)
    for _ in range(3):
        telemetry.step_begin()
        telemetry.step_end(examples=4)
    from mxnet_tpu.telemetry import memwatch
    report_path = str(tmp_path / "oom.json")
    memwatch.write_postmortem(path=report_path, context="test",
                              error="RESOURCE_EXHAUSTED (fake)")
    with open(report_path) as f:
        report = json.load(f)
    assert "recent_steps" in report
    assert [r["step"] for r in report["recent_steps"]] == [1, 2, 3]


# --- live /metrics endpoint --------------------------------------------------

def test_metrics_endpoint_scrape_equals_telemetry_counters():
    telemetry.enable()
    fleet.enable(stride=10_000, http_port=0)
    telemetry.count("trainer.allreduce_bytes", 1234)
    telemetry.count("fleet.unit_test", 3)
    url = fleet.metrics_url()
    assert url is not None
    body = urllib.request.urlopen(url + "/metrics",
                                  timeout=10).read().decode()
    # every telemetry counter appears verbatim on the scrape (the
    # acceptance: live /metrics == the job's telemetry counters)
    for name, value in telemetry.counters().items():
        fam = "mxt_" + name.replace(".", "_") + "_total"
        assert f"{fam} {int(value)}" in body, (fam, body)
    assert "mxt_fleet_rank 0" in body
    assert "mxt_fleet_world_size 1" in body
    health = json.loads(urllib.request.urlopen(
        url + "/healthz", timeout=10).read().decode())
    assert health["status"] == "ok" and health["rank"] == 0
    telemetry.disable()
    assert fleet.metrics_url() is None


# --- profiler bridge ---------------------------------------------------------

def test_profiler_span_args_carry_rank_when_fleet_on(tmp_path):
    from mxnet_tpu import profiler

    trace = str(tmp_path / "prof.json")
    profiler.set_config(filename=trace)
    profiler.dump(finished=True)
    telemetry.enable()
    fleet.enable(stride=10_000)
    profiler.set_state("run")
    try:
        with telemetry.span("trainer.step"):
            pass
    finally:
        profiler.dump(finished=True)
        telemetry.disable()
    events = json.load(open(trace))["traceEvents"]
    evt = next(e for e in events if e.get("cat") == "telemetry")
    assert str(evt["args"]["rank"]) == "0"
    assert str(evt["args"]["world_size"]) == "1"


# --- read_jsonl multi-path / glob merge -------------------------------------

def test_read_jsonl_merges_streams_by_step_and_rank(tmp_path):
    a, b = tmp_path / "fleet.rank0.jsonl", tmp_path / "fleet.rank1.jsonl"
    a.write_text("".join(json.dumps({"step": s, "rank": 0}) + "\n"
                         for s in (1, 2, 3)))
    b.write_text("".join(json.dumps({"step": s, "rank": 1}) + "\n"
                         for s in (1, 2, 3)))
    merged = read_jsonl([str(a), str(b)])
    assert [(r["step"], r["rank"]) for r in merged] == \
        [(1, 0), (1, 1), (2, 0), (2, 1), (3, 0), (3, 1)]
    assert not merged.truncated
    globbed = read_jsonl(str(tmp_path / "fleet.rank*.jsonl"))
    assert list(globbed) == list(merged)


def test_read_jsonl_merge_tolerates_one_truncated_tail(tmp_path):
    a, b = tmp_path / "r0.jsonl", tmp_path / "r1.jsonl"
    a.write_text(json.dumps({"step": 1, "rank": 0}) + "\n"
                 + '{"step": 2, "ran')  # writer died mid-record
    b.write_text(json.dumps({"step": 1, "rank": 1}) + "\n")
    merged = read_jsonl([str(a), str(b)])
    assert merged.truncated
    assert [(r["step"], r["rank"]) for r in merged] == [(1, 0), (1, 1)]
    # single-path behavior is unchanged
    single = read_jsonl(str(a))
    assert single.truncated and len(single) == 1


# --- SIGTERM-drain dump roundtrip through fleet_report ----------------------

def test_drain_dump_roundtrips_through_fleet_report(tmp_path, monkeypatch,
                                                    capsys):
    dump_tmpl = str(tmp_path / "drain.rank{rank}.json")
    monkeypatch.setenv("MXNET_FLEET_DUMP", dump_tmpl)
    telemetry.enable()
    fleet.enable(stride=2)
    net = gluon.nn.Dense(2)
    net.initialize(mx.init.Xavier())
    net(nd.ones((1, 3)))
    for _ in range(6):
        telemetry.step_begin()
        telemetry.count("trainer.allreduce_wait_ms", 1.0)
        telemetry.step_end(examples=4, loss=0.25)
    with pytest.raises(SystemExit) as ei:
        checkpoint.drain_checkpoint_and_exit(str(tmp_path / "ck"), 6, net)
    assert ei.value.code == trainer_mod.PREEMPTED_EXIT_CODE
    path = dump_tmpl.replace("{rank}", "0")
    with open(path) as f:
        doc = json.load(f)
    assert doc["reason"] == "preemption_drain"
    assert doc["context"] == {"step": 6}
    steps = [r for r in doc["records"] if r.get("record") is None]
    assert len(steps) == 6

    fleet_report = _fleet_report()
    assert fleet_report.main([path]) == 0
    out = capsys.readouterr().out
    assert "fleet heatmap" in out
    assert "6 step, 3 fleet view, 0 anomaly" in out
    chrome_out = str(tmp_path / "tl.json")
    assert fleet_report.main([path, "--format", "chrome",
                              "--out", chrome_out]) == 0
    with open(chrome_out) as f:
        tl = json.load(f)
    assert sum(1 for e in tl["traceEvents"] if e["ph"] == "X") == 6
    names = {e["args"]["name"] for e in tl["traceEvents"]
             if e["ph"] == "M"}
    assert names == {"rank 0"}


# --- dp2 CPU-mesh chaos lane: straggler named, dump survives SIGKILL --------

def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _run(cmd, env, timeout=420):
    proc = subprocess.Popen(cmd, env=env, start_new_session=True,
                            stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT, text=True)
    try:
        log, _ = proc.communicate(timeout=timeout)
    except subprocess.TimeoutExpired:
        os.killpg(os.getpgid(proc.pid), signal.SIGKILL)
        raise
    return proc.returncode, log


@pytest.mark.skipif(sys.platform != "linux", reason="loopback group")
def test_chaos_fleet_names_straggler_and_dump_survives_sigkill(tmp_path):
    d = str(tmp_path)
    env = dict(os.environ)
    env.update(REPO_ROOT=REPO, CKPT_DIR=d + "/ck", TOTAL_STEPS="36",
               OUT_FILE=d + "/out_", STEP_SLEEP="0",
               MXT_LAUNCH_PLATFORM="cpu",
               FLEET_JSONL=d + "/fleet.rank", FLEET_STRIDE="4",
               SLOW_RANK="1", SLOW_SLEEP="0.08",
               MXNET_FLEET_WINDOWS="2")
    dump_tmpl = d + "/fd.rank{rank}.json"
    summary_file = d + "/chaos.json"
    rc, log = _run(
        [sys.executable, os.path.join(REPO, "tools", "chaos.py"),
         "-n", "2", "--kills", "1", "--mix", "kill", "--seed", "5",
         "--min-delay", "1.0", "--max-delay", "2.5",
         "--max-restarts", "6", "--backoff-base", "0.1",
         "--coordinator", f"127.0.0.1:{_free_port()}",
         "--summary", summary_file, "--fleet-dump", dump_tmpl,
         "--", sys.executable, WORKER], env)
    assert rc == 0, log[-3000:]
    with open(summary_file) as f:
        summary = json.load(f)
    assert summary["survived"]
    assert summary["injections"], summary
    assert all(i["signal"] == "SIGKILL" for i in summary["injections"])
    # a flight dump exists and is readable for every killed rank...
    assert summary["fleet_dumps_complete"], summary
    for _rank, path in summary["fleet_dumps"].items():
        with open(path) as f:
            doc = json.load(f)
        assert doc["record"] == "flight_recorder"
        assert doc["kind"] == "fleet"
        # ...embedding that rank's last >= 16 step records
        steps = [r for r in doc["records"] if r.get("record") is None
                 and "step_ms" in r]
        assert len(steps) >= 16, len(steps)

    # the merged per-rank streams name rank 1 as the straggler
    merged = read_jsonl(d + "/fleet.rank*.jsonl")
    views = [r for r in merged if r.get("record") == "fleet"]
    assert views
    flagged = [v for v in views if 1 in v.get("stragglers", [])]
    assert flagged, [v.get("stragglers") for v in views]
    anomalies = [r for r in merged if r.get("record") == "anomaly"
                 and r.get("kind") == "straggler"]
    assert anomalies
    assert all(a["culprit"] == 1 for a in anomalies), anomalies

    # ...and so does the report CLI, text and Perfetto both
    fleet_report = _fleet_report()
    rep = d + "/report.txt"
    assert fleet_report.main([d + "/fleet.rank*.jsonl",
                              "--out", rep]) == 0
    text = open(rep).read()
    straggler_line = next(ln for ln in text.splitlines()
                          if ln.startswith("stragglers"))
    assert "rank 1 (" in straggler_line, text
    tl_path = d + "/timeline.json"
    assert fleet_report.main([d + "/fleet.rank*.jsonl", "--format",
                              "chrome", "--out", tl_path]) == 0
    with open(tl_path) as f:
        tl = json.load(f)
    tracks = {e["args"]["name"] for e in tl["traceEvents"]
              if e["ph"] == "M"}
    assert {"rank 0", "rank 1"} <= tracks
    assert any(e["ph"] == "i" and e["name"] == "anomaly:straggler"
               for e in tl["traceEvents"])
