"""Lock-sanitizer unit tests (mxnet_tpu.sanitizer, MXNET_SANITIZE_LOCKS):
order-edge recording, cycle detection, held-while-blocking events, the
Condition protocol, the trace-hook stream, and the disabled-path
one-boolean overhead bound."""
import threading
import time

import pytest

from mxnet_tpu import sanitizer


@pytest.fixture(autouse=True)
def _clean_sanitizer():
    was = sanitizer.locks_enabled()
    sanitizer.reset_locks()
    yield
    sanitizer.set_trace_hook(None)
    if was:
        sanitizer.enable_locks()
    else:
        sanitizer.disable_locks()
    sanitizer.reset_locks()


def test_env_var_gate(monkeypatch):
    for val, want in [("1", True), ("on", True), ("TRUE", True),
                      ("0", False), ("off", False), ("", False),
                      ("no", False)]:
        monkeypatch.setenv("MXNET_SANITIZE_LOCKS", val)
        assert sanitizer._locks_env_on() is want, val
    monkeypatch.delenv("MXNET_SANITIZE_LOCKS")
    assert sanitizer._locks_env_on() is False


def test_order_edges_recorded_for_nested_acquisition():
    sanitizer.enable_locks()
    a = sanitizer.wrap_lock(threading.Lock(), "t.san.A")
    b = sanitizer.wrap_lock(threading.Lock(), "t.san.B")
    with a:
        with b:
            pass
    edges = sanitizer.lock_order_edges()
    assert ("t.san.A", "t.san.B") in edges
    assert ("t.san.B", "t.san.A") not in edges
    assert sanitizer.lock_order_violations() == []


def test_cycle_detected_across_opposite_orders():
    sanitizer.enable_locks()
    a = sanitizer.wrap_lock(threading.Lock(), "t.cyc.A")
    b = sanitizer.wrap_lock(threading.Lock(), "t.cyc.B")
    with a:
        with b:
            pass
    with b:
        with a:
            pass
    cycles = sanitizer.lock_order_violations()
    assert cycles, "opposite acquisition orders must report a cycle"
    assert {"t.cyc.A", "t.cyc.B"} <= set(cycles[0])


def test_held_while_blocking_event_recorded():
    sanitizer.enable_locks()
    x = sanitizer.wrap_lock(threading.Lock(), "t.blk.X")
    y = sanitizer.wrap_lock(threading.Lock(), "t.blk.Y")
    holding = threading.Event()
    release = threading.Event()

    def holder():
        with x:
            holding.set()
            release.wait(10)

    t = threading.Thread(target=holder, name="mxt-test-holder",
                         daemon=True)
    t.start()
    assert holding.wait(10)
    with y:
        assert not x.acquire(timeout=0.2)   # contended while holding y
    release.set()
    t.join(timeout=10)
    assert ("t.blk.Y", "t.blk.X",
            threading.current_thread().name) \
        in sanitizer.held_blocking_events()


def test_condition_wait_pops_held_stack():
    sanitizer.enable_locks()
    cond = sanitizer.wrap_lock(threading.Condition(), "t.cond.C")
    other = sanitizer.wrap_lock(threading.Lock(), "t.cond.L")
    fired = []

    def notifier():
        with cond:
            fired.append(True)
            cond.notify_all()

    t = threading.Timer(0.05, notifier)
    t.start()
    with cond:
        assert cond.wait_for(lambda: fired, timeout=10)
        # the wait released C: a lock taken during it by the notifier
        # thread never saw C on OUR stack; taking one now does
        with other:
            pass
    t.join()
    assert ("t.cond.C", "t.cond.L") in sanitizer.lock_order_edges()
    assert sanitizer.lock_order_violations() == []


def test_trace_hook_sees_acquire_stream_and_restores():
    sanitizer.enable_locks()
    a = sanitizer.wrap_lock(threading.Lock(), "t.hook.A")
    events = []
    prev = sanitizer.set_trace_hook(
        lambda ev, name: events.append((ev, name)))
    try:
        with a:
            pass
    finally:
        restored = sanitizer.set_trace_hook(prev)
    assert events == [("acquire", "t.hook.A"),
                      ("acquired", "t.hook.A"),
                      ("released", "t.hook.A")]
    assert restored is not None


def test_reset_forgets_edges_keeps_enabled_state():
    sanitizer.enable_locks()
    a = sanitizer.wrap_lock(threading.Lock(), "t.rst.A")
    b = sanitizer.wrap_lock(threading.Lock(), "t.rst.B")
    with a, b:
        pass
    assert sanitizer.lock_order_edges()
    sanitizer.reset_locks()
    assert sanitizer.lock_order_edges() == {}
    assert sanitizer.held_blocking_events() == []
    assert sanitizer.locks_enabled()


def test_delegation_surface():
    sanitizer.enable_locks()
    lk = sanitizer.wrap_lock(threading.RLock(), "t.del.R")
    assert lk.acquire()
    assert lk.acquire()          # reentrant through the proxy
    lk.release()
    lk.release()
    assert "t.del.R" in repr(lk)
    c = sanitizer.wrap_lock(threading.Condition(), "t.del.C")
    with c:
        c.notify_all()           # __getattr__ delegation


def test_disabled_path_is_one_boolean_check():
    """MXNET_SANITIZE_LOCKS unset: acquire/release cost one global read
    plus delegation — same bound style as telemetry's null path
    (tests/test_memwatch.py)."""
    sanitizer.disable_locks()
    lk = sanitizer.wrap_lock(threading.Lock(), "t.fast.L")
    t0 = time.perf_counter()
    for _ in range(10_000):
        with lk:
            pass
    assert time.perf_counter() - t0 < 0.5
    assert sanitizer.lock_order_edges() == {}
