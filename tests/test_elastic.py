"""Elastic data-parallel resize (round 6 tentpole, layer 3).

The contract: the global batch at step ``k`` is a pure function of
(seed, step) — NEVER of world size — and each rank takes a contiguous
slice.  Growing or shrinking the group between (re)launches therefore
replays the exact same global batch sequence, so a 2→1→2-worker run
resumed from checkpoints follows the same parameter trajectory as a
fresh run at ANY fixed size.

Unit tests pin the sharding algebra; the integration test drives an
actual resize through tools/launch.py + checkpoint.resume.
"""
import os
import signal
import socket
import subprocess
import sys

import numpy as np
import pytest

from mxnet_tpu import elastic
from mxnet_tpu.base import MXNetError

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
WORKER = os.path.join(REPO, "tests", "_preempt_worker.py")


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


# --- unit: the sharding algebra ---------------------------------------------

def test_global_batch_is_deterministic_and_step_dependent():
    a = elastic.global_batch_indices(100, 8, step=3, seed=7)
    b = elastic.global_batch_indices(100, 8, step=3, seed=7)
    c = elastic.global_batch_indices(100, 8, step=4, seed=7)
    d = elastic.global_batch_indices(100, 8, step=3, seed=8)
    assert (a == b).all()
    assert not (a == c).all() or not (a == d).all()
    assert len(a) == 8 and a.min() >= 0 and a.max() < 100
    assert len(set(a.tolist())) == 8  # without-replacement draw


def test_shards_partition_the_global_batch():
    """Any world size slices the SAME global batch: concatenating the
    rank shards in rank order reproduces it exactly."""
    for step in (0, 1, 17):
        full = elastic.global_batch_indices(64, 8, step, seed=5)
        for world in (1, 2, 4, 8):
            parts = [elastic.shard_indices(full, world, r)
                     for r in range(world)]
            assert (np.concatenate(parts) == full).all(), (step, world)
            assert all(len(p) == 8 // world for p in parts)


def test_shard_for_step_matches_manual_slicing():
    got = elastic.shard_for_step(64, 8, step=2, world_size=2, rank=1,
                                 seed=5)
    full = elastic.global_batch_indices(64, 8, step=2, seed=5)
    assert (got == full[4:]).all()


def test_sequential_mode_wraps_around():
    idx = elastic.global_batch_indices(10, 4, step=2, shuffle=False)
    assert idx.tolist() == [8, 9, 0, 1]


def test_indivisible_batch_raises():
    with pytest.raises(MXNetError, match="divide"):
        elastic.shard_indices(np.arange(8), world_size=3, rank=0)
    with pytest.raises(MXNetError):
        elastic.global_batch_indices(64, 8, step=-1)


def test_world_info_reads_launcher_env(monkeypatch):
    monkeypatch.setenv("MXT_PROCESS_ID", "1")
    monkeypatch.setenv("MXT_NUM_PROCESSES", "4")
    assert elastic.world_info() == (1, 4)


# --- integration: 2 → 1 → 2 resize through real launches --------------------

def _launch(n, ckpt, total, out, loss, port, timeout=300):
    env = dict(os.environ)
    env.update(REPO_ROOT=REPO, CKPT_DIR=ckpt, TOTAL_STEPS=str(total),
               OUT_FILE=out, LOSS_FILE=loss, MXT_LAUNCH_PLATFORM="cpu")
    proc = subprocess.Popen(
        [sys.executable, os.path.join(REPO, "tools", "launch.py"),
         "-n", str(n), "--coordinator", f"127.0.0.1:{port}",
         sys.executable, WORKER],
        env=env, start_new_session=True, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True)
    try:
        log, _ = proc.communicate(timeout=timeout)
    except subprocess.TimeoutExpired:
        os.killpg(os.getpgid(proc.pid), signal.SIGKILL)
        raise
    assert proc.returncode == 0, log[-3000:]
    return log


def _losses(path):
    """step → loss, keeping the LAST occurrence (steps between the last
    checkpoint and a fault are re-trained and re-logged on resume)."""
    out = {}
    with open(path) as f:
        for line in f:
            step, loss = line.split()
            out[int(step)] = float(loss)
    return [out[k] for k in sorted(out)]


@pytest.mark.skipif(sys.platform != "linux", reason="loopback group")
def test_elastic_resize_2_1_2_matches_fixed_size_runs(tmp_path):
    """Acceptance: train 2 workers → resume with 1 → resume with 2
    again; per-step losses match FRESH fixed-size runs (both sizes) and
    the final params match the oracle."""
    total = 6
    d = str(tmp_path)
    seg = [("a", 2, 2), ("b", 1, 4), ("c", 2, 6)]  # (tag, world, until)
    for tag, world, until in seg:
        log = _launch(world, d + "/ck", until, f"{d}/seg_{tag}_",
                      f"{d}/loss_resized", _free_port())
        if tag != "a":
            assert "resumed from step" in log, log[-2000:]

    _launch(2, d + "/ck2", total, f"{d}/o2_", f"{d}/loss_w2", _free_port())
    _launch(1, d + "/ck1", total, f"{d}/o1_", f"{d}/loss_w1", _free_port())

    resized = _losses(f"{d}/loss_resized")
    for oracle_file in ("loss_w2", "loss_w1"):
        oracle = _losses(f"{d}/{oracle_file}")
        assert len(resized) == len(oracle) == total
        np.testing.assert_allclose(resized, oracle, rtol=1e-5,
                                   err_msg=oracle_file)

    final = np.load(f"{d}/seg_c_0.npy")
    np.testing.assert_allclose(final, np.load(f"{d}/o2_0.npy"), rtol=1e-5)
    np.testing.assert_allclose(final, np.load(f"{d}/o1_0.npy"), rtol=1e-5)
