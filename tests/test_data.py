"""Data-plane tests: recordio format, io iterators, gluon.data, image
(reference: tests/python/unittest/test_recordio.py:?, test_io.py:?,
test_gluon_data.py:?)."""
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon, nd, recordio
from mxnet_tpu.gluon.data import (ArrayDataset, BatchSampler, DataLoader,
                                  RandomSampler, SequentialSampler,
                                  SimpleDataset)


# --- recordio ---------------------------------------------------------------

def test_recordio_roundtrip(tmp_path):
    path = str(tmp_path / "test.rec")
    writer = recordio.MXRecordIO(path, "w")
    for i in range(5):
        writer.write(f"record-{i}".encode() * (i + 1))
    writer.close()
    reader = recordio.MXRecordIO(path, "r")
    for i in range(5):
        assert reader.read() == f"record-{i}".encode() * (i + 1)
    assert reader.read() is None
    reader.close()


def test_indexed_recordio(tmp_path):
    rec_path = str(tmp_path / "test.rec")
    idx_path = str(tmp_path / "test.idx")
    writer = recordio.MXIndexedRecordIO(idx_path, rec_path, "w")
    for i in range(10):
        writer.write_idx(i, f"data{i}".encode())
    writer.close()
    reader = recordio.MXIndexedRecordIO(idx_path, rec_path, "r")
    assert reader.read_idx(7) == b"data7"
    assert reader.read_idx(2) == b"data2"
    assert reader.keys == list(range(10))
    reader.close()


def test_irheader_pack_unpack():
    header = recordio.IRHeader(0, 3.5, 42, 0)
    packed = recordio.pack(header, b"payload")
    got, payload = recordio.unpack(packed)
    assert payload == b"payload"
    assert got.label == 3.5
    assert got.id == 42
    # array label
    header2 = recordio.IRHeader(0, np.array([1.0, 2.0], np.float32), 1, 0)
    got2, _ = recordio.unpack(recordio.pack(header2, b"x"))
    assert np.allclose(got2.label, [1.0, 2.0])


def test_pack_img_roundtrip(tmp_path):
    img = np.random.RandomState(0).randint(0, 255, (16, 16, 3), np.uint8)
    packed = recordio.pack_img(recordio.IRHeader(0, 1.0, 0, 0), img,
                               img_fmt=".png")
    header, decoded = recordio.unpack_img(packed)
    assert header.label == 1.0
    assert np.array_equal(decoded, img)  # png is lossless


# --- io iterators -----------------------------------------------------------

def test_ndarray_iter():
    data = np.arange(40, dtype=np.float32).reshape(10, 4)
    label = np.arange(10, dtype=np.float32)
    it = mx.io.NDArrayIter(data, label, batch_size=4,
                           last_batch_handle="pad")
    batches = list(it)
    assert len(batches) == 3
    assert batches[0].data[0].shape == (4, 4)
    assert batches[2].pad == 2
    it.reset()
    assert len(list(it)) == 3


def test_ndarray_iter_discard():
    it = mx.io.NDArrayIter(np.zeros((10, 2)), np.zeros(10), batch_size=4,
                           last_batch_handle="discard")
    assert len(list(it)) == 2


def test_ndarray_iter_shuffle():
    data = np.arange(10, dtype=np.float32).reshape(10, 1)
    it = mx.io.NDArrayIter(data, data[:, 0], batch_size=10, shuffle=True)
    batch = next(iter(it))
    assert not np.array_equal(batch.data[0].asnumpy().ravel(),
                              np.arange(10))
    assert np.array_equal(np.sort(batch.data[0].asnumpy().ravel()),
                          np.arange(10))


def test_image_record_iter(tmp_path):
    rec_path = str(tmp_path / "imgs.rec")
    idx_path = str(tmp_path / "imgs.idx")
    writer = recordio.MXIndexedRecordIO(idx_path, rec_path, "w")
    rng = np.random.RandomState(0)
    for i in range(8):
        img = rng.randint(0, 255, (20, 20, 3), np.uint8)
        writer.write_idx(i, recordio.pack_img(
            recordio.IRHeader(0, float(i % 3), i, 0), img, img_fmt=".png"))
    writer.close()
    it = mx.io.ImageRecordIter(path_imgrec=rec_path, path_imgidx=idx_path,
                               data_shape=(3, 16, 16), batch_size=4,
                               shuffle=True, rand_crop=True,
                               rand_mirror=True)
    batches = list(iter(it))
    assert len(batches) == 2
    assert batches[0].data[0].shape == (4, 3, 16, 16)
    assert batches[0].label[0].shape == (4,)


def test_prefetching_iter():
    base = mx.io.NDArrayIter(np.arange(24).reshape(12, 2).astype(np.float32),
                             np.arange(12), batch_size=4)
    it = mx.io.PrefetchingIter(base)
    batches = list(it)
    assert len(batches) == 3
    it.reset()
    assert len(list(it)) == 3


# --- gluon.data -------------------------------------------------------------

def test_array_dataset_and_loader():
    x = np.random.rand(20, 5).astype(np.float32)
    y = np.arange(20, dtype=np.float32)
    ds = ArrayDataset(x, y)
    assert len(ds) == 20
    sample_x, sample_y = ds[3]
    assert np.allclose(sample_x, x[3])
    loader = DataLoader(ds, batch_size=6, last_batch="keep")
    batches = list(loader)
    assert len(batches) == 4
    assert batches[0][0].shape == (6, 5)
    assert batches[-1][0].shape == (2, 5)


def test_dataloader_shuffle_and_discard():
    ds = ArrayDataset(np.arange(10, dtype=np.float32))
    loader = DataLoader(ds, batch_size=3, shuffle=True, last_batch="discard")
    batches = list(loader)
    assert len(batches) == 3
    seen = np.sort(np.concatenate([b.asnumpy() for b in batches]))
    assert len(seen) == 9


def test_dataloader_workers():
    ds = ArrayDataset(np.arange(32, dtype=np.float32).reshape(16, 2),
                      np.arange(16, dtype=np.float32))
    loader = DataLoader(ds, batch_size=4, num_workers=3)
    batches = list(loader)
    assert len(batches) == 4
    # order preserved despite parallel fetch
    assert np.allclose(batches[0][1].asnumpy(), [0, 1, 2, 3])


def test_dataset_transform():
    ds = SimpleDataset(list(range(10))).transform(lambda x: x * 2)
    assert ds[4] == 8
    ds2 = ArrayDataset(np.ones((4, 2), np.float32),
                       np.zeros(4, np.float32)).transform_first(
        lambda x: x + 1)
    x, y = ds2[0]
    assert np.allclose(x, 2)


def test_samplers():
    assert list(SequentialSampler(4)) == [0, 1, 2, 3]
    assert sorted(RandomSampler(5)) == list(range(5))
    bs = BatchSampler(SequentialSampler(7), 3, "keep")
    assert [len(b) for b in bs] == [3, 3, 1]
    bs2 = BatchSampler(SequentialSampler(7), 3, "discard")
    assert [len(b) for b in bs2] == [3, 3]


def test_transforms_pipeline():
    from mxnet_tpu.gluon.data.vision import transforms

    img = nd.array(np.random.randint(0, 255, (20, 24, 3)).astype(np.uint8))
    t = transforms.ToTensor()(img)
    assert t.shape == (3, 20, 24)
    assert float(t.max().asscalar()) <= 1.0
    norm = transforms.Normalize(mean=(0.5, 0.5, 0.5),
                                std=(0.5, 0.5, 0.5))(t)
    assert norm.shape == (3, 20, 24)
    composed = transforms.Compose([
        transforms.Resize(16),
        transforms.CenterCrop(12),
        transforms.ToTensor(),
    ])
    out = composed(img)
    assert out.shape == (3, 12, 12)


def test_random_resized_crop():
    from mxnet_tpu.gluon.data.vision import transforms

    img = nd.array(np.random.randint(0, 255, (32, 32, 3)).astype(np.uint8))
    out = transforms.RandomResizedCrop(16)(img)
    assert out.shape[:2] == (16, 16)


def test_synthetic_dataset_with_loader_end_to_end():
    from mxnet_tpu.gluon.data.vision import SyntheticImageDataset
    from mxnet_tpu.gluon.data.vision import transforms

    tfm = transforms.Compose([transforms.ToTensor()])
    ds = SyntheticImageDataset(length=16, shape=(8, 8, 3), classes=4) \
        .transform_first(lambda x: tfm(x))
    loader = DataLoader(ds, batch_size=8)
    x, y = next(iter(loader))
    assert x.shape == (8, 3, 8, 8)
    assert y.shape == (8,)


def test_record_file_dataset(tmp_path):
    rec_path = str(tmp_path / "ds.rec")
    idx_path = str(tmp_path / "ds.idx")
    writer = recordio.MXIndexedRecordIO(idx_path, rec_path, "w")
    for i in range(4):
        writer.write_idx(i, f"item{i}".encode())
    writer.close()
    ds = gluon.data.RecordFileDataset(rec_path)
    assert len(ds) == 4
    assert ds[2] == b"item2"


def test_dataloader_prefetch_bounded():
    """Workers must not race more than the prefetch window ahead of the
    consumer (unbounded racing would buffer the whole dataset)."""
    import threading
    import time

    from mxnet_tpu.gluon.data import DataLoader

    fetched = []
    lock = threading.Lock()

    class Spy:
        def __len__(self):
            return 64

        def __getitem__(self, i):
            with lock:
                fetched.append(i)
            return np.float32(i)

    loader = DataLoader(Spy(), batch_size=1, num_workers=4, prefetch=4)
    max_ahead = 0
    for n_consumed, _batch in enumerate(loader):
        time.sleep(0.005)  # slow consumer lets workers run ahead
        with lock:
            max_ahead = max(max_ahead, len(fetched) - (n_consumed + 1))
    assert len(fetched) == 64
    # window = max(prefetch, workers) = 4, +workers in flight slack
    assert max_ahead <= 4 + 4 + 1, f"prefetch unbounded: {max_ahead}"


def test_dataloader_threaded_matches_serial():
    from mxnet_tpu.gluon.data import ArrayDataset, DataLoader

    x = np.arange(40, dtype=np.float32).reshape(20, 2)
    ds = ArrayDataset(nd.array(x))
    serial = [b.asnumpy() for b in DataLoader(ds, batch_size=4)]
    threaded = [b.asnumpy()
                for b in DataLoader(ds, batch_size=4, num_workers=3)]
    assert len(serial) == len(threaded)
    for a, b in zip(serial, threaded):
        np.testing.assert_array_equal(a, b)


# --- process-based workers (shared-memory handoff) --------------------------

class _SquareDataset:
    """Picklable dataset with a CPU-bound python transform."""

    def __init__(self, n):
        self._x = np.arange(n * 3, dtype=np.float32).reshape(n, 3)

    def __getitem__(self, i):
        return self._x[i] ** 2, np.float32(i)

    def __len__(self):
        return len(self._x)


def test_dataloader_process_matches_serial():
    from mxnet_tpu.gluon.data import DataLoader

    ds = _SquareDataset(21)
    serial = [(d.asnumpy(), l.asnumpy())
              for d, l in DataLoader(ds, batch_size=4)]
    loader = DataLoader(ds, batch_size=4, num_workers=2,
                        worker_type="process")
    try:
        proc = [(d.asnumpy(), l.asnumpy()) for d, l in loader]
        assert len(serial) == len(proc)
        for (a, al), (b, bl) in zip(serial, proc):
            np.testing.assert_array_equal(a, b)
            np.testing.assert_array_equal(al, bl)
        # epoch 2 reuses the persistent pool, same order
        proc2 = [(d.asnumpy(), l.asnumpy()) for d, l in loader]
        for (a, al), (b, bl) in zip(serial, proc2):
            np.testing.assert_array_equal(a, b)
    finally:
        loader.close()


class _FailingDataset:
    def __getitem__(self, i):
        if i == 5:
            raise ValueError("boom at 5")
        return np.zeros(2, np.float32)

    def __len__(self):
        return 8


def test_dataloader_process_error_propagates():
    import pytest

    from mxnet_tpu.gluon.data import DataLoader

    loader = DataLoader(_FailingDataset(), batch_size=2, num_workers=2,
                        worker_type="process")
    try:
        with pytest.raises(mx.MXNetError, match="boom at 5"):
            list(loader)
    finally:
        loader.close()


def test_dataloader_thread_pool_flag_forces_threads():
    from mxnet_tpu.gluon.data import ArrayDataset, DataLoader

    x = np.arange(12, dtype=np.float32).reshape(6, 2)
    loader = DataLoader(ArrayDataset(nd.array(x)), batch_size=2,
                        num_workers=2, worker_type="process",
                        thread_pool=True)
    assert loader._worker_type == "thread"
    out = [b.asnumpy() for b in loader]
    np.testing.assert_array_equal(np.concatenate(out), x)


def test_dataloader_process_abandoned_epoch_no_poison():
    """Breaking out mid-epoch must not leak the old epoch's batches into
    the next iteration (epoch-tagged jobs/results)."""
    from mxnet_tpu.gluon.data import DataLoader

    ds = _SquareDataset(24)
    loader = DataLoader(ds, batch_size=4, num_workers=2,
                        worker_type="process", prefetch=4)
    try:
        it = iter(loader)
        next(it)  # abandon with jobs still queued/in flight
        del it
        serial = [(d.asnumpy(), l.asnumpy())
                  for d, l in DataLoader(ds, batch_size=4)]
        again = [(d.asnumpy(), l.asnumpy()) for d, l in loader]
        assert len(serial) == len(again)
        for (a, al), (b, bl) in zip(serial, again):
            np.testing.assert_array_equal(a, b)
            np.testing.assert_array_equal(al, bl)
    finally:
        loader.close()


def test_dataloader_process_no_shm_leak():
    """Every shared-memory block is unlinked, including abandoned-epoch
    and shutdown-time results."""
    import glob
    import time

    from mxnet_tpu.gluon.data import DataLoader

    before = set(glob.glob("/dev/shm/psm_*"))
    ds = _SquareDataset(32)
    loader = DataLoader(ds, batch_size=4, num_workers=2,
                        worker_type="process", prefetch=6)
    it = iter(loader)
    next(it)
    del it          # abandoned epoch: leftovers freed on next use/close
    list(loader)    # full epoch
    loader.close()  # shutdown drains in-flight results
    for _ in range(50):
        leaked = set(glob.glob("/dev/shm/psm_*")) - before
        if not leaked:
            break
        time.sleep(0.1)
    assert not leaked, f"leaked shm segments: {leaked}"


def test_shm_sweep_start_time_token():
    """The stale-shm sweep keys liveness on pid + /proc start ticks
    (ADVICE r3): a live owner's block survives, a dead/recycled owner's
    block is reclaimed, and legacy bare-pid names need BOTH a dead pid
    and an old mtime before they're touched."""
    import os
    import time as _time

    from mxnet_tpu.gluon.data import dataloader as dl

    if not os.path.isdir("/dev/shm"):
        pytest.skip("no /dev/shm")

    me = os.getpid()
    ticks = dl._proc_start_ticks(me)
    assert ticks is not None and ticks > 0
    # a pid that can't exist → dead owner
    dead_pid = 2 ** 22 + 12345

    live = f"mxt-{me}-{ticks}-deadbeef0001"
    recycled = f"mxt-{me}-{ticks + 777}-deadbeef0002"  # pid alive, ticks differ
    dead = f"mxt-{dead_pid}-12345-deadbeef0003"
    legacy = f"mxt-{dead_pid}-deadbeef0004"            # bare-pid name
    paths = {}
    for name in (live, recycled, dead, legacy):
        p = os.path.join("/dev/shm", name)
        with open(p, "w") as f:
            f.write("x")
        paths[name] = p
    try:
        # fresh blocks: NOTHING is reclaimed, even with a dead owner —
        # the age gate protects live foreign-namespace owners whose
        # pid/ticks we can't verify (shared /dev/shm mounts)
        dl._sweep_stale_shm()
        for name, p in paths.items():
            assert os.path.exists(p), f"fresh block swept: {name}"
        # age everything past the threshold → dead/recycled reclaimed,
        # verifiably-live owner's block still kept
        old = _time.time() - dl._SHM_SWEEP_MIN_AGE - 5
        for p in paths.values():
            os.utime(p, (old, old))
        dl._sweep_stale_shm()
        assert os.path.exists(paths[live]), "live owner's block swept"
        assert not os.path.exists(paths[recycled]), \
            "recycled-pid block not reclaimed"
        assert not os.path.exists(paths[dead]), "dead-owner block kept"
        assert not os.path.exists(paths[legacy]), \
            "aged legacy block with dead owner not reclaimed"
    finally:
        for p in paths.values():
            try:
                os.unlink(p)
            except OSError:
                pass


def test_augment_basic_matches_device_numeric_stage():
    """The host-side augment_basic reference chain and ImageRecordIter's
    device-side numeric stage must never diverge."""
    from mxnet_tpu.image import augment_basic
    from mxnet_tpu.io import _numeric_finish

    rs = np.random.RandomState(0)
    img = rs.randint(0, 255, (12, 12, 3), np.uint8)
    mean, std, scale = (123.0, 117.0, 104.0), (58.0, 57.0, 57.0), 2.0
    host = augment_basic(img, (3, 12, 12), rs, mean=mean, std=std,
                         scale=scale)
    dev = np.asarray(_numeric_finish(mean, std, scale)(img[None]))[0]
    np.testing.assert_allclose(dev, host, rtol=1e-6)
