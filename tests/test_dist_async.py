"""dist_async parameter-server kvstore tests.

Reference test model: tests/nightly/dist_async_kvstore.py:? — workers push
without barriers, server applies updates on arrival; plus the single-process
async-engine contract (push returns before the update lands, pull drains).
"""
import threading

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.kvstore.dist_async import (AsyncPSKVStore, PSServer,
                                          serve_forever)
from mxnet_tpu.test_utils import assert_almost_equal


def _free_port():
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@pytest.fixture
def ps_secret(monkeypatch):
    """Remote set_optimizer requires HMAC-signed frames (what
    tools/launch.py provides via MXT_PS_SECRET)."""
    monkeypatch.setenv("MXT_PS_SECRET", "test-job-secret")


def test_embedded_push_pull_replaces():
    kv = AsyncPSKVStore()
    kv.init(3, nd.ones((2, 3)))
    out = nd.zeros((2, 3))
    for i in range(4):
        kv.push(3, nd.ones((2, 3)) * (i + 1))
    kv.pull(3, out=out)
    # no updater: the last pushed value replaces the stored one (matches
    # KVStoreLocal — keeps the Trainer push-grad/pull-grad path correct)
    assert_almost_equal(out, np.full((2, 3), 4.0))
    kv.close()


def test_embedded_server_side_sgd():
    kv = mx.kv.create("dist_async")
    assert kv.type == "dist_async"
    kv.init("w", nd.ones((4,)))
    kv.set_optimizer(mx.optimizer.SGD(learning_rate=0.5))
    kv.push("w", nd.ones((4,)) * 2.0)  # w -= 0.5 * 2
    out = nd.zeros((4,))
    kv.pull("w", out=out)
    assert_almost_equal(out, np.zeros((4,)))  # 1 - 0.5*2
    kv.close()


def test_async_push_is_nonblocking_and_fifo():
    kv = AsyncPSKVStore()
    kv.init(0, nd.zeros((1000, 100)))
    # lr=-1 SGD turns every push into "+= grad": 50 pushes => 50.0
    kv.set_optimizer(mx.optimizer.SGD(learning_rate=-1.0))
    for i in range(50):
        kv.push(0, nd.ones((1000, 100)))
    kv.wait_all()
    out = nd.zeros((1000, 100))
    kv.pull(0, out=out)
    assert_almost_equal(out, np.full((1000, 100), 50.0))
    kv.close()


def test_tcp_two_workers_concurrent(ps_secret):
    port = _free_port()
    uri = f"127.0.0.1:{port}"
    srv = serve_forever(uri, PSServer())
    try:
        w0 = AsyncPSKVStore(root_uri=uri, rank=0, num_workers=2)
        w1 = AsyncPSKVStore(root_uri=uri, rank=1, num_workers=2)
        w0.init("k", nd.zeros((64,)))
        w1.init("k", nd.zeros((64,)))  # second init is a no-op
        w0.set_optimizer(mx.optimizer.SGD(learning_rate=-1.0))

        def hammer(kv, n):
            for _ in range(n):
                kv.push("k", nd.ones((64,)))
            kv.wait_all()

        t0 = threading.Thread(target=hammer, args=(w0, 20))
        t1 = threading.Thread(target=hammer, args=(w1, 30))
        t0.start(); t1.start(); t0.join(); t1.join()
        out = nd.zeros((64,))
        w0.pull("k", out=out)
        assert_almost_equal(out, np.full((64,), 50.0))
        w0.close(); w1.close()
    finally:
        srv.shutdown()


def test_tcp_server_side_optimizer_no_barrier(ps_secret):
    port = _free_port()
    uri = f"127.0.0.1:{port}"
    srv = serve_forever(uri, PSServer())
    try:
        w0 = AsyncPSKVStore(root_uri=uri, rank=0, num_workers=2)
        w1 = AsyncPSKVStore(root_uri=uri, rank=1, num_workers=2)
        w0.init("w", nd.ones((8,)) * 10.0)
        w0.set_optimizer(mx.optimizer.SGD(learning_rate=1.0))
        # worker 1 pushes alone — dist_async applies immediately, no
        # waiting for worker 0 (the sync mode would block here)
        w1.push("w", nd.ones((8,)))
        w1.wait_all()
        out = nd.zeros((8,))
        w1.pull("w", out=out)
        assert_almost_equal(out, np.full((8,), 9.0))
        w0.close(); w1.close()
    finally:
        srv.shutdown()


def test_row_sparse_pull_tcp():
    from mxnet_tpu.ndarray import sparse as sp

    port = _free_port()
    uri = f"127.0.0.1:{port}"
    srv = serve_forever(uri, PSServer())
    try:
        kv = AsyncPSKVStore(root_uri=uri)
        table = np.arange(20, dtype=np.float32).reshape(10, 2)
        kv.init("emb", nd.array(table))
        out = sp.zeros("row_sparse", (10, 2))
        kv.row_sparse_pull("emb", out=out, row_ids=nd.array([1, 7]))
        dense = out.todense().asnumpy()
        assert_almost_equal(dense[1], table[1])
        assert_almost_equal(dense[7], table[7])
        # dense target: only requested rows overwritten
        dt = nd.ones((10, 2)) * -1.0
        kv.row_sparse_pull("emb", out=dt, row_ids=nd.array([3]))
        got = dt.asnumpy()
        assert_almost_equal(got[3], table[3])
        assert_almost_equal(got[0], [-1.0, -1.0])
        kv.close()
    finally:
        srv.shutdown()


def test_error_surfaces_at_sync_point():
    kv = AsyncPSKVStore()
    kv.init("a", nd.ones((2,)))
    kv.push("a", nd.ones((2,)))
    kv._enqueue("push", "nope", ("dense", np.ones((2,))))  # uninitialized
    with pytest.raises(Exception):
        kv.wait_all()
    kv.close()


def test_trainer_dist_async_matches_local():
    """Single worker: dist_async (server-side SGD) must produce the exact
    same weights as local training — the end-to-end Trainer contract."""
    from mxnet_tpu import autograd, gluon

    results = []
    for kvname in (None, "dist_async"):
        mx.random.seed(7)
        net = gluon.nn.Dense(3)
        net.initialize(mx.init.Xavier())
        net(nd.ones((2, 5)))  # resolve deferred shapes
        trainer = gluon.Trainer(net.collect_params(), "sgd",
                                {"learning_rate": 0.1, "momentum": 0.9},
                                kvstore=kvname)
        x = nd.array(np.random.RandomState(0).randn(2, 5)
                     .astype(np.float32))
        for _ in range(3):
            with autograd.record():
                loss = (net(x) ** 2).mean()
            loss.backward()
            trainer.step(2)
        results.append(net.weight.data().asnumpy())
        if kvname == "dist_async":
            trainer._kvstore.close()
    assert_almost_equal(results[0], results[1], rtol=1e-5, atol=1e-6)


def test_trainer_dist_async_rejects_client_update():
    from mxnet_tpu import gluon

    net = gluon.nn.Dense(2)
    net.initialize(mx.init.Xavier())
    net(nd.ones((1, 4)))
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            kvstore="dist_async", update_on_kvstore=False)
    with pytest.raises(Exception):
        trainer._init_kvstore()


def test_trainer_fm_style_sparse_training():
    """Factorization-machine style: embedding-ish weight trained via
    dist_async PS push/pull (the BASELINE config 4 shape)."""
    np.random.seed(0)
    kv = mx.kv.create("dist_async")
    w = nd.array(np.random.randn(6, 3).astype(np.float32))
    kv.init("w", w)
    kv.set_optimizer(mx.optimizer.SGD(learning_rate=0.1))
    before = None
    for step in range(5):
        grad = nd.array(np.random.randn(6, 3).astype(np.float32))
        kv.push("w", grad)
        out = nd.zeros((6, 3))
        kv.pull("w", out=out)
        if before is not None:
            assert not np.allclose(before, out.asnumpy())
        before = out.asnumpy()
    kv.close()


# --- wire-security contract (non-executable frames, HMAC gating) ------------

def test_tcp_unsigned_set_optimizer_refused(monkeypatch):
    """Without MXT_PS_SECRET, remote set_optimizer (the one pickled
    payload) must be refused; the non-executable data path still works."""
    monkeypatch.delenv("MXT_PS_SECRET", raising=False)
    port = _free_port()
    uri = f"127.0.0.1:{port}"
    srv = serve_forever(uri, PSServer())
    try:
        kv = AsyncPSKVStore(root_uri=uri)
        kv.init("k", nd.ones((4,)))          # data commands: fine unsigned
        out = nd.zeros((4,))
        kv.pull("k", out=out)
        assert_almost_equal(out, np.ones((4,)))
        with pytest.raises(mx.MXNetError, match="MXT_PS_SECRET"):
            kv.set_optimizer(mx.optimizer.SGD(learning_rate=0.1))
        kv.close()
    finally:
        srv.shutdown()


def test_tcp_signature_mismatch_rejected():
    """A worker with the wrong secret fails the connection challenge and
    cannot complete a round-trip."""
    port = _free_port()
    uri = f"127.0.0.1:{port}"
    srv = serve_forever(uri, PSServer(), secret="server-secret")
    try:
        kv = AsyncPSKVStore(root_uri=uri, secret="worker-secret")
        with pytest.raises(Exception):
            kv.init("k", nd.ones((4,)))
        kv._sock.close()  # server dropped the connection; don't send bye
        kv._local = PSServer()  # neutralize close() path
        kv._sock = None
        kv.close()
    finally:
        srv.shutdown()


def test_tcp_hparam_resync(ps_secret):
    """set_optimizer_hparams refreshes lr server-side without resetting
    optimizer state (the Trainer.step re-sync path)."""
    port = _free_port()
    uri = f"127.0.0.1:{port}"
    srv = serve_forever(uri, PSServer())
    try:
        kv = AsyncPSKVStore(root_uri=uri)
        kv.init("w", nd.ones((4,)) * 10.0)
        kv.set_optimizer(mx.optimizer.SGD(learning_rate=1.0))
        kv.push("w", nd.ones((4,)))          # 10 - 1*1 = 9
        kv.set_optimizer_hparams(lr=0.5)
        kv.push("w", nd.ones((4,)))          # 9 - 0.5*1 = 8.5
        out = nd.zeros((4,))
        kv.pull("w", out=out)
        assert_almost_equal(out, np.full((4,), 8.5))
        kv.close()
    finally:
        srv.shutdown()


def test_trainer_hparam_change_propagates_to_ps():
    """Trainer.set_learning_rate + a changed batch_size reach the
    (embedded) PS server before the next update (ADVICE round-1 fix)."""
    from mxnet_tpu import autograd, gluon

    net = gluon.nn.Dense(1, use_bias=False)
    net.initialize(mx.init.Constant(0.0))
    net(nd.ones((1, 2)))
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.0},  # first step: no-op
                            kvstore="dist_async")
    x = nd.ones((2, 2))
    with autograd.record():
        loss = net(x).sum()
    loss.backward()
    trainer.step(2)
    w0 = net.weight.data().asnumpy().copy()
    assert_almost_equal(w0, np.zeros_like(w0))  # lr=0 did nothing
    trainer.set_learning_rate(0.5)              # must reach the server
    with autograd.record():
        loss = net(x).sum()
    loss.backward()
    trainer.step(2)
    w1 = net.weight.data().asnumpy()
    assert not np.allclose(w1, w0), "stale lr=0 stayed on the PS server"
    trainer._kvstore.close()


def test_tcp_secretless_client_rejected_at_connect():
    """Server with a secret challenges at connect; a secretless client
    fails immediately (pre-auth), before any frame is buffered."""
    port = _free_port()
    uri = f"127.0.0.1:{port}"
    srv = serve_forever(uri, PSServer(), secret="server-secret")
    try:
        with pytest.raises(mx.MXNetError, match="MXT_PS_SECRET"):
            AsyncPSKVStore(root_uri=uri)
    finally:
        srv.shutdown()


def test_generate_oracle_path_rejects_beyond_context():
    """The guard covers the uncached/MoE oracle path too, not just the
    KV-cache path."""
    import mxnet_tpu as mx2
    from mxnet_tpu.models import llama as ll

    net = ll.llama_tiny()
    net.initialize(mx.init.Xavier())
    with pytest.raises(mx.MXNetError, match="max_seq_len"):
        net.generate(nd.array(np.zeros((1, 4)), dtype="int32"),
                     max_new_tokens=200, use_cache=False)


def test_frame_signature_binds_nonce_and_sequence():
    """A signed frame is not valid under another nonce, direction, or
    sequence position — the anti-replay property."""
    from mxnet_tpu.kvstore import dist_async as da

    secret, nonce = b"s3cret", b"n" * 16
    frame = da._pack_frame(("push", "k"), secret, nonce, b"C", 5)
    payload = frame[8:]
    msg, signed = da._unpack_frame(payload, secret, nonce, b"C", 5)
    assert signed and msg[0] == "push"
    for bad in [(secret, b"m" * 16, b"C", 5),   # other connection
                (secret, nonce, b"S", 5),        # reflected
                (secret, nonce, b"C", 6)]:       # replayed later
        with pytest.raises(mx.MXNetError, match="signature mismatch"):
            da._unpack_frame(payload, *bad)


def test_secret_worker_rejects_unauthenticated_server():
    """Worker configured with a secret must refuse to talk to a server
    that runs unauthenticated (clear connect-time diagnostic)."""
    port = _free_port()
    uri = f"127.0.0.1:{port}"
    srv = serve_forever(uri, PSServer(), secret=None)
    try:
        with pytest.raises(mx.MXNetError, match="UNAUTHENTICATED"):
            AsyncPSKVStore(root_uri=uri, secret="worker-secret")
    finally:
        srv.shutdown()
