"""Text generation with the KV-cache decoder.

Runs a (tiny, randomly initialised) Llama through the jitted
prefill+decode path: greedy and nucleus sampling.  With a real checkpoint,
swap in ``llama3_8b()`` + ``net.load_parameters(...)``.

Usage:  python examples/generate_llama.py
"""
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.models import llama


def main():
    mx.random.seed(0)
    net = llama.llama_tiny(attn_mode="sdpa", max_seq_len=512)
    net.initialize(mx.init.Xavier())

    prompt = nd.array(np.random.RandomState(0).randint(0, 256, (1, 8)),
                      dtype="int32")
    greedy = net.generate(prompt, max_new_tokens=32)
    print("greedy :", greedy.asnumpy()[0, 8:].tolist())

    sampled = net.generate(prompt, max_new_tokens=32, do_sample=True,
                           temperature=0.8, top_p=0.95, top_k=50, seed=7)
    print("sampled:", sampled.asnumpy()[0, 8:].tolist())

    # the decoder object is reusable and exposes throughput-style decode
    dec = llama.LlamaDecoder(net, max_len=256)
    import time

    dec.generate(prompt._data, 100)  # warm the compile
    t0 = time.perf_counter()
    dec.generate(prompt._data, 100)
    dt = time.perf_counter() - t0
    print(f"decode throughput: {100 / dt:.0f} tok/s (batch 1)")


if __name__ == "__main__":
    main()
