"""Mixtral-style sparse-MoE training with expert parallelism.

Single process: trains mixtral-tiny with the top-k router + aux
load-balancing loss (eager path).  On a mesh, ``shard_llama`` puts the
expert bank on the ``ep`` axis and GSPMD derives the token all-to-all.

Usage:  python examples/train_moe.py
"""
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd
from mxnet_tpu.models import llama, moe


def main():
    mx.random.seed(0)
    net = llama.mixtral_tiny(attn_mode="sdpa")  # top-k router
    net.initialize(mx.init.Xavier())
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 3e-3})

    rs = np.random.RandomState(0)
    ids = nd.array(rs.randint(0, 256, (8, 32)), dtype="int32")
    labels = nd.array(np.roll(ids.asnumpy(), -1, axis=1), dtype="int32")
    for step in range(20):
        with moe.collect_aux() as aux:
            with autograd.record():
                logits = net(ids)
                ce = nd.softmax_cross_entropy(
                    logits.reshape((-1, 256)),
                    labels.reshape((-1,))).mean()
                loss = ce + 0.01 * sum(aux, nd.zeros(()))
            loss.backward()
        trainer.step(8)
        if step % 5 == 0:
            print(f"step {step}: ce {float(ce.asscalar()):.3f} "
                  f"(aux x{len(aux)})")
    print("done")


if __name__ == "__main__":
    main()
