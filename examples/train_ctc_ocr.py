"""OCR-style CTC training on synthetic sequences.

Demonstrates round-2 capabilities end to end:
- ``gluon.loss.CTCLoss`` over an LSTM encoder (reference
  ``example/ctc/``-style workload: variable-length targets, blank=last);
- process-based DataLoader workers (``worker_type='process'`` —
  spawned, shared-memory handoff; note the ``__main__`` guard, which
  spawned workers REQUIRE);
- the NaiveEngine debug lever: rerun with ``MXT_ENGINE_TYPE=NaiveEngine``
  to bisect failures op-by-op.

Run: python examples/train_ctc_ocr.py
"""
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import autograd, gluon, nd  # noqa: E402

N_CLASSES = 10          # digits; class 10 is the CTC blank ('last')
SEQ_LEN = 32            # input time steps
MAX_LABEL = 6


class SyntheticOCR:
    """Picklable dataset: each sample is a (T, 8) 'feature strip' built
    from a random digit string, labels padded with -1."""

    def __init__(self, n):
        self.n = n

    def __getitem__(self, i):
        rs = np.random.RandomState(i)
        length = rs.randint(2, MAX_LABEL + 1)
        digits = rs.randint(0, N_CLASSES, length)
        xs = np.zeros((SEQ_LEN, 8), np.float32)
        span = SEQ_LEN // length
        for j, d in enumerate(digits):
            xs[j * span:(j + 1) * span, d % 8] = 1.0
        xs += rs.randn(SEQ_LEN, 8).astype(np.float32) * 0.1
        label = np.full((MAX_LABEL,), -1, np.float32)
        label[:length] = digits
        return xs, label

    def __len__(self):
        return self.n


class CTCNet(gluon.HybridBlock):
    def __init__(self, **kw):
        super().__init__(**kw)
        with self.name_scope():
            self.encoder = gluon.rnn.LSTM(32, num_layers=1,
                                          layout="NTC", bidirectional=True)
            self.head = gluon.nn.Dense(N_CLASSES + 1, flatten=False)

    def hybrid_forward(self, F, x):
        return self.head(self.encoder(x))  # (N, T, C+1)


def main():
    mx.random.seed(0)
    net = CTCNet()
    net.initialize(mx.init.Xavier())
    net(nd.ones((1, SEQ_LEN, 8)))
    net.hybridize(static_alloc=True)
    loss_fn = gluon.loss.CTCLoss(layout="NTC")
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 3e-3})
    loader = gluon.data.DataLoader(SyntheticOCR(512), batch_size=32,
                                   num_workers=2, worker_type="process")
    try:
        for epoch in range(3):
            total, batches = 0.0, 0
            for x, y in loader:
                with autograd.record():
                    loss = loss_fn(net(x), y).mean()
                loss.backward()
                trainer.step(x.shape[0])
                total += float(loss.asscalar())
                batches += 1
            print(f"epoch {epoch}: ctc loss {total / batches:.3f}")
    finally:
        loader.close()


if __name__ == "__main__":
    main()
