#!/usr/bin/env python
"""Image classification training (reference:
``example/image-classification/train_cifar10.py:?`` style, BASELINE
config 1).

Synthetic CIFAR-shaped data by default so it runs anywhere; pass
``--rec path.rec`` (from ``tools/im2rec.py``) for a real RecordIO
pipeline.  One-line context swap: everything below is the reference's
Gluon training loop; ``mx.tpu()`` + ``dist_tpu_sync`` are the only
TPU-isms.
"""
from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd


def synthetic_batches(batch, steps, classes=10):
    rng = np.random.RandomState(0)
    for _ in range(steps):
        x = nd.array(rng.uniform(0, 1, (batch, 3, 32, 32))
                     .astype(np.float32))
        y = nd.array(rng.randint(0, classes, (batch,)))
        yield x, y


def recordio_batches(rec, batch, steps):
    from mxnet_tpu import io

    it = io.ImageRecordIter(path_imgrec=rec, data_shape=(3, 32, 32),
                            batch_size=batch, shuffle=True)
    n = 0
    while n < steps:
        it.reset()
        got_any = False
        for b in it:
            got_any = True
            yield b.data[0], b.label[0]
            n += 1
            if n >= steps:
                return
        if not got_any:
            raise RuntimeError(f"{rec!r} yielded no batches")


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--model", default="resnet18_v1")
    p.add_argument("--batch", type=int, default=64)
    p.add_argument("--steps", type=int, default=30)
    p.add_argument("--lr", type=float, default=0.05)
    p.add_argument("--rec", default=None)
    p.add_argument("--amp", action="store_true")
    args = p.parse_args()

    mx.random.seed(42)
    net = gluon.model_zoo.vision.get_model(args.model, classes=10)
    net.initialize(mx.init.Xavier())
    net(nd.ones((1, 3, 32, 32)))  # resolve deferred shapes cheaply
    if args.amp:
        from mxnet_tpu import amp

        amp.init(target_dtype="bfloat16")
    net.hybridize(static_alloc=True)
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": args.lr, "momentum": 0.9},
                            kvstore="device")
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    metric = mx.metric.Accuracy()
    # auto_reset=False: keep whole-run accuracy for the summary line
    speed = mx.callback.Speedometer(args.batch, frequent=10,
                                    auto_reset=False)

    batches = (recordio_batches(args.rec, args.batch, args.steps)
               if args.rec else
               synthetic_batches(args.batch, args.steps))
    tic = None
    timed = 0
    for i, (x, y) in enumerate(batches):
        with autograd.record():
            out = net(x)
            loss = loss_fn(out, y)
        loss.backward()
        trainer.step(args.batch)
        metric.update(y, out)
        speed(mx.callback.BatchEndParam(epoch=0, nbatch=i,
                                        eval_metric=metric, locals=None))
        if tic is None:   # first step paid XLA compile; time the rest
            nd.waitall()
            tic = time.time()
        else:
            timed += 1
    nd.waitall()
    name, acc = metric.get()
    ips = args.batch * timed / (time.time() - tic) if timed else 0.0
    print(f"done: {args.steps} steps, {ips:.0f} img/s (steady state), "
          f"{name}={acc:.3f}")


if __name__ == "__main__":
    main()
