#!/usr/bin/env python
"""Llama pretraining step benchmark over a device mesh (BASELINE stretch
config 5 — capability the reference never had).

Single chip: ``python examples/train_llama.py --layers 4 --hidden 512``.
Virtual multi-chip (any machine):
``XLA_FLAGS=--xla_force_host_platform_device_count=8 BENCH_PLATFORM=cpu \
python examples/train_llama.py --mesh dp2,tp2,sp2 --attn ring``
"""
from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def parse_mesh(spec):
    out = {}
    for part in spec.split(","):
        name = part.rstrip("0123456789")
        out[name] = int(part[len(name):])
    return out


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--layers", type=int, default=4)
    p.add_argument("--hidden", type=int, default=512)
    p.add_argument("--heads", type=int, default=8)
    p.add_argument("--kv-heads", type=int, default=4)
    p.add_argument("--vocab", type=int, default=2048)
    p.add_argument("--seq", type=int, default=512)
    p.add_argument("--batch", type=int, default=8)
    p.add_argument("--steps", type=int, default=10)
    p.add_argument("--mesh", default=None, help="e.g. dp2,tp2,sp2")
    p.add_argument("--attn", default="flash",
                   choices=["flash", "sdpa", "ring", "ulysses"])
    args = p.parse_args()

    plat = os.environ.get("BENCH_PLATFORM")
    if plat:
        import jax

        jax.config.update("jax_platforms", plat)

    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import autograd, gluon, nd, parallel
    from mxnet_tpu.models import llama

    mx.random.seed(0)
    cfg = dict(hidden_size=args.hidden,
               intermediate_size=int(args.hidden * 2.75),
               num_layers=args.layers, num_heads=args.heads,
               num_kv_heads=args.kv_heads, vocab_size=args.vocab,
               max_seq_len=args.seq, attn_mode=args.attn)
    mesh = parallel.make_mesh(parse_mesh(args.mesh)) if args.mesh else None
    scope = parallel.mesh_scope(mesh) if mesh else None
    if scope:
        scope.__enter__()
    try:
        net = llama.LlamaForCausalLM(llama.LlamaConfig(**cfg))
        net.initialize(mx.init.Xavier())
        if mesh:
            llama.shard_llama(net, mesh)
        net.hybridize(static_alloc=True)
        trainer = gluon.Trainer(
            net.collect_params(), "adam", {"learning_rate": 3e-4},
            kvstore="dist_tpu_sync" if mesh else "device")
        rng = np.random.RandomState(0)
        ids = nd.array(rng.randint(0, args.vocab,
                                   (args.batch, args.seq)), dtype="int32")
        labels = nd.array(rng.randint(0, args.vocab,
                                      (args.batch, args.seq)),
                          dtype="int32")
        if mesh:
            ids = parallel.shard_batch(ids, mesh)
            labels = parallel.shard_batch(labels, mesh)

        ntok = args.batch * args.seq

        def step():
            with autograd.record():
                logits = net(ids)
                # softmax_cross_entropy SUMS over tokens (reference
                # contract); normalize to per-token loss
                loss = nd.softmax_cross_entropy(
                    logits.reshape((-1, args.vocab)),
                    labels.reshape((-1,))) / ntok
            loss.backward()
            # loss already per-token; step(1) keeps rescale_grad = 1
            trainer.step(1)
            return loss

        step().wait_to_read()  # compile
        tic = time.time()
        for _ in range(args.steps):
            loss = step()
        loss.wait_to_read()
        wall = time.time() - tic
        toks = args.batch * args.seq * args.steps / wall
        print(f"mesh={dict(mesh.shape) if mesh else None} "
              f"attn={args.attn}: {toks:.0f} tok/s, "
              f"loss={float(loss.asscalar()):.3f}")
    finally:
        if scope:
            scope.__exit__(None, None, None)


if __name__ == "__main__":
    main()
