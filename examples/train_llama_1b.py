"""Largest-fits-one-chip Llama pretraining (BASELINE config 5 half of the
8B scale proof — tools/llama8b_proof.py carries the multi-chip lowering;
this trains a real ~1.2B decoder on the single v5e).

Canonical config (the README's measured ~10.9k tok/s row): hidden 2304,
18 layers, 18 heads (head_dim 128, GQA kv 6), SwiGLU ffn 6144, vocab
32k, seq 2048 → 1.17B parameters.  Env overrides reach other scales:
``LAYERS=20`` → 1.28B (also fits, SGD-mom only), and the on-chip
crash-resume proof ran at 0.83B (``LAYERS=12``) to leave room for the
checkpoint writer.  Fit strategy (VERDICT
r2's "~1.3-1.5B with remat + bf16"): parameters cast to bf16
(`net.cast`), optimizer state rides the param dtype, activation
rematerialization via `hybridize(remat=True)`, flash attention.  At
bf16+remat the resident footprint is ~6 bytes/param + layer-boundary
activations — ~9 GiB of the 16 GiB HBM.

Run: PYTHONPATH=/root/repo python examples/train_llama_1b.py
(env: STEPS=300 BATCH=4 SEQ=2048 LOG_EVERY=20)

Fit note (2026-08-02): a tunnel-backend update shrank the largest
single-program training footprint that executes — the 1.17B default
that trained in r3 now OOMs (r3 code verbatim reproduces it; PERF_NOTES
"cont. 4").  Configs measured green on the current backend:
``LAYERS=8`` (0.60B, 27.3k tok/s) and ``LAYERS=12 BATCH=2`` (0.83B,
18.4k tok/s).
"""
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import autograd, checkpoint, gluon, nd
from mxnet_tpu.models import llama


def main():
    steps = int(os.environ.get("STEPS", "300"))
    batch = int(os.environ.get("BATCH", "4"))
    seq = int(os.environ.get("SEQ", "2048"))
    log_every = int(os.environ.get("LOG_EVERY", "20"))
    vocab = 32000

    mx.random.seed(0)
    layers = int(os.environ.get("LAYERS", "18"))
    net = llama.LlamaForCausalLM(llama.LlamaConfig(
        hidden_size=2304, intermediate_size=6144, num_layers=layers,
        num_heads=18, num_kv_heads=6, vocab_size=vocab,
        max_seq_len=seq, attn_mode="flash",
        # SCAN_LAYERS=1: lax.scan over the stacked decoder — layer-
        # count-independent compile, one layer's buffers, per-iteration
        # remat; costs one recorded weight restack per step (r4)
        scan_layers=bool(int(os.environ.get("SCAN_LAYERS", "0")))))
    net.initialize(mx.init.Normal(0.02))
    net(nd.ones((1, 8), dtype="int32"))  # resolve deferred shapes cheaply
    n_params = sum(int(np.prod(p.shape))
                   for p in net.collect_params().values())
    print(f"params: {n_params/1e9:.2f}B")
    net.cast("bfloat16")
    net.hybridize(static_alloc=True, remat=True)
    # SGD+momentum: 8 bytes/param resident (bf16 p+g, f32 momentum) vs
    # Adam's 16 (f32 m AND v for bf16 weights) — the difference between
    # 1.17B fitting and OOM on a 16 GiB chip
    opt = os.environ.get("OPT", "sgd")
    hp = {"learning_rate": float(os.environ.get("LR", "1e-3"))}
    if opt == "sgd":
        hp["momentum"] = 0.9
    trainer = gluon.Trainer(net.collect_params(), opt, hp)

    rng = np.random.RandomState(0)
    ids_np = rng.randint(0, vocab, (batch, seq + 1))
    ids = nd.array(ids_np[:, :-1], dtype="int32")
    labels = nd.array(ids_np[:, 1:], dtype="int32")

    # loss-in-graph: the token CE compiles as its own CachedOp instead
    # of three eager dispatches per step (host dispatch is the scarce
    # resource through the tunnel — bench.py's protocol, +11% measured
    # on the ResNet leg)
    class _TokenCE(gluon.HybridBlock):
        def hybrid_forward(self, F, logits, lab):
            return F.softmax_cross_entropy(
                logits.reshape((-1, vocab)),
                lab.reshape((-1,))) / (batch * seq)

    loss_fn = _TokenCE()
    loss_fn.hybridize()

    def step():
        with autograd.record():
            loss = loss_fn(net(ids), labels)
        loss.backward()
        trainer.step(1)
        return loss

    # D10 at scale: CKPT_DIR enables periodic atomic checkpoints and
    # crash-resume — a rerun with the same dir continues from the newest
    # complete step instead of restarting.  Every optimizer update is a
    # counted step (the compile-paying first iteration included), so the
    # resumed trajectory is update-for-update identical to an
    # uninterrupted run.
    ckpt_dir = os.environ.get("CKPT_DIR")
    ckpt_every = int(os.environ.get("CKPT_EVERY", "100"))
    start = 0
    if ckpt_dir:
        start, _ = checkpoint.resume(ckpt_dir, net, trainer)
        if start:
            print(f"resumed from step {start}")
    if start >= steps - 1:
        print(json.dumps({"model": f"llama_h2304_l{layers}",
                          "resumed_at": start, "steps": steps,
                          "note": "nothing left to train"}))
        return

    print("compiling...")
    t0 = time.time()
    tok_per_step = batch * seq
    tic = time.time()
    win = 0  # steps measured in the current window (resets with tic so
    best = 0.0  # checkpoint wall time never pollutes a tok/s sample)
    last = None
    first = None
    for i in range(start + 1, steps):
        last = step()
        if first is None:
            last.wait_to_read()
            first = float(last.asscalar())
            print(f"first step {time.time()-t0:.0f}s loss={first:.3f}")
            tic, win = time.time(), 0
        else:
            win += 1
        if win >= log_every:
            # scalar fetch BEFORE reading the clock: through the tunnel
            # wait_to_read can return at dispatch, and a window closed
            # that way measures enqueue rate, not compute (the r4 MFU
            # audit caught bench.py's old protocol pricing BERT >100%
            # of peak) — only a host fetch proves the work is done
            lv = float(last.asscalar())
            dt = time.time() - tic
            tps = win * tok_per_step / dt
            best = max(best, tps)
            print(f"step {i:4d} loss={lv:.3f} {tps:,.0f} tok/s")
            tic, win = time.time(), 0
        if ckpt_dir and i % ckpt_every == 0:
            last.wait_to_read()
            checkpoint.save_checkpoint(ckpt_dir, i, net, trainer, keep=2)
            tic, win = time.time(), 0
    final = float(last.asscalar())
    # model FLOPs: 6N per token fwd+bwd (remat recompute excluded — the
    # standard accounting); MFU vs 197 bf16 TFLOP/s
    mfu = best * 6 * n_params / 197e12
    print(json.dumps({
        "model": f"llama_h2304_l{layers}", "params": n_params,
        "seq": seq, "batch": batch, "optimizer": opt,
        "first_loss": round(first, 3), "final_loss": round(final, 3),
        "best_tok_per_sec": round(best, 0), "mfu_6N": round(mfu, 3)}))


if __name__ == "__main__":
    main()
