"""Train → export → serve with the standalone predictor.

The gluon side exports a compiled StableHLO artifact + .params; the
serving side needs only ``mxnet_tpu.predictor`` (MXPredCreate-style
surface, SURVEY §3.5).

Usage:  python examples/serve_predictor.py
"""
import tempfile

import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import mxnet_tpu as mx
from mxnet_tpu import gluon, nd
from mxnet_tpu.predictor import create


def main():
    # --- training side -----------------------------------------------------
    mx.random.seed(0)
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Conv2D(16, 3, padding=1, activation="relu"),
            gluon.nn.GlobalAvgPool2D(), gluon.nn.Dense(10))
    net.initialize(mx.init.Xavier())
    x = nd.array(np.random.RandomState(0)
                 .randn(4, 3, 32, 32).astype(np.float32))
    net.hybridize()
    net(x)  # one forward so a cached graph exists
    prefix = tempfile.mkdtemp() + "/cnn"
    net.export(prefix, epoch=0)
    print("exported", prefix + "-symbol.json")

    # --- serving side ------------------------------------------------------
    pred = create(f"{prefix}-symbol.json", f"{prefix}-0000.params")
    pred.set_input(pred.input_names[0], x)
    pred.forward()
    probs = nd.softmax(pred.get_output(0))
    print("top-1 per image:", probs.asnumpy().argmax(axis=1).tolist())


if __name__ == "__main__":
    main()
