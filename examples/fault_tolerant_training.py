"""Preemption-safe training: atomic checkpoints + auto-resume.

Kill this script at any point and re-run it — training continues from the
newest complete checkpoint with bitwise-identical optimizer state.

Usage:  python examples/fault_tolerant_training.py [ckpt_dir]
"""
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import mxnet_tpu as mx
from mxnet_tpu import autograd, checkpoint, gluon, nd

TOTAL_STEPS = 200
CKPT_EVERY = 20


def main(ckpt_dir="/tmp/mxt_ft_ckpts"):
    mx.random.seed(0)
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(64, activation="relu"), gluon.nn.Dense(10))
    net.initialize(mx.init.Xavier())
    net(nd.ones((2, 32)))
    net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.05, "momentum": 0.9})
    trainer._init_kvstore()

    start, extra = checkpoint.resume(ckpt_dir, net, trainer)
    if start:
        print(f"resumed from step {start} (loss was {extra.get('loss')})")

    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    rs = np.random.RandomState(0)
    x = nd.array(rs.randn(64, 32).astype(np.float32))
    y = nd.array(rs.randint(0, 10, (64,)))
    for step in range(start + 1, TOTAL_STEPS + 1):
        with autograd.record():
            loss = loss_fn(net(x), y).mean()
        loss.backward()
        trainer.step(64)
        if step % CKPT_EVERY == 0:
            val = float(loss.asscalar())
            checkpoint.save_checkpoint(ckpt_dir, step, net, trainer,
                                       extra={"loss": val}, keep=3)
            print(f"step {step}: loss {val:.4f} (checkpointed)")
    print("done")


if __name__ == "__main__":
    main(*sys.argv[1:])
