#!/usr/bin/env python
"""Sparse linear classification on high-dimensional CSR features.

Reference analog: ``example/sparse/linear_classification/train.py:?`` —
logistic regression over sparse criteo-style features with row_sparse
weight updates.  TPU-native shape of the same workflow:

- the feature matrix stays CSR end to end (cast_storage, stored-entry
  scaling, structure-preserving unary, BCOO-backed sparse dot — none of
  these densify, see ndarray/sparse.py);
- the dense weight's gradient flows THROUGH the sparse dot (the BCOO
  matmul's vjp; the gradient itself is dense — on TPU the scatter of a
  row_sparse gradient would cost more than the dense update it saves,
  so the row_sparse-gradient path is reserved for the huge-embedding
  workloads that opt in via sparse_grad, see ops/nn_ops.embedding);
- the forward/backward compute runs through the same jitted XLA path
  every framework op uses.

Run:  python examples/sparse_linear_classification.py
Env:  N=40000 D=4096 DENSITY=0.02 STEPS=40 BATCH=512
"""
import os

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd, nd
from mxnet_tpu.ndarray import sparse as sp


def synthetic_sparse_problem(n, d, density, seed=0):
    """y = sign(x @ w_true) over a sparse x (each row has ~density*d
    active features)."""
    rs = np.random.RandomState(seed)
    x = rs.randn(n, d).astype(np.float32)
    x[rs.rand(n, d) > density] = 0.0
    w_true = rs.randn(d).astype(np.float32)
    y = (x @ w_true > 0).astype(np.float32)
    return x, y


def main():
    n = int(os.environ.get("N", "40000"))
    d = int(os.environ.get("D", "4096"))
    density = float(os.environ.get("DENSITY", "0.02"))
    steps = int(os.environ.get("STEPS", "40"))
    batch = int(os.environ.get("BATCH", "512"))

    x_np, y_np = synthetic_sparse_problem(n, d, density)

    # normalize features WITHOUT densifying: bound outliers via the
    # structure-preserving sparse ops (scalar kernel + zero-preserving
    # unary; tanh keeps the bulk near-linear and clips the tails)
    x_csr = nd.array(x_np).tostype("csr")
    x_csr = x_csr * 1.0             # stored-entry scalar kernel
    x_csr = nd.tanh(x_csr)          # bounded features, still CSR
    assert x_csr.stype == "csr"
    print(f"features: {x_csr.shape} csr, nnz={x_csr.data.shape[0]} "
          f"({x_csr.data.shape[0] / (n * d):.1%})")

    w = nd.zeros((d, 1))
    w.attach_grad()
    b = nd.zeros((1,))
    b.attach_grad()
    opt = mx.optimizer.SGD(learning_rate=float(
        os.environ.get("LR", "5.0")))
    states = {"w": opt.create_state(0, w), "b": opt.create_state(1, b)}

    # one host copy of the NORMALIZED features for batching (row
    # slicing is the DataLoader sampler's job; training and the
    # full-set eval below must see the SAME feature matrix)
    xn_np = x_csr.asnumpy()

    rs = np.random.RandomState(1)
    losses = []
    for step in range(steps):
        idx = rs.randint(0, n, batch)
        # batch rows, re-sparsified (host index math, device values)
        xb = nd.array(xn_np[idx]).tostype("csr")
        yb = nd.array(y_np[idx].reshape(-1, 1))
        with autograd.record():
            logits = nd.dot(xb, w) + b    # BCOO sparse matmul
            loss = nd.log_softmax(
                nd.concat(nd.zeros_like(logits), logits, dim=1))
            nll = -(yb * loss[:, 1:2] + (1 - yb) * loss[:, 0:1])
            nll = nll.mean()
        nll.backward()
        for name, p in (("w", w), ("b", b)):
            opt.update(0 if name == "w" else 1, p, p.grad, states[name])
        losses.append(float(nll.asscalar()))
        if step % 10 == 0 or step == steps - 1:
            pred = (nd.dot(x_csr, w) + b).asnumpy().ravel() > 0
            acc = float((pred == (y_np > 0.5)).mean())
            print(f"step {step:3d}  loss {losses[-1]:.4f}  "
                  f"full-set acc {acc:.3f}")

    assert losses[-1] < losses[0] * 0.8, (losses[0], losses[-1])
    print("converged: loss", round(losses[0], 3), "->",
          round(losses[-1], 3))


if __name__ == "__main__":
    main()
