// Dependency engine: the TPU build's native analog of MXNet's
// ThreadedEnginePerDevice (reference: src/engine/threaded_engine.{h,cc},
// include/mxnet/engine.h — SURVEY §2.1 #1).
//
// Semantics reproduced exactly:
//   * ops are pushed with declared read-var and write-var sets;
//   * conflicting ops (any write overlap) execute in program order,
//     non-conflicting ops run in parallel across a worker pool;
//   * reads on the same var are concurrent; a write is exclusive and
//     ordered after every earlier read/write of that var;
//   * WaitForVar blocks until every pushed op touching the var completed;
//     WaitForAll drains the engine.
//
// On TPU the device-side scheduling job belongs to XLA's async dispatch —
// this engine schedules the HOST side: record IO, decode, prefetch and any
// user async task (exposed to python through ctypes callbacks).
//
// Design notes vs the reference: one global mutex guarding var state (host
// task granularity here is file/decode work, ~ms; the reference needed
// finer locking for ~us GPU op dispatch), FIFO grant queues per var give
// the same serialization the reference gets from its var queues.

#include <condition_variable>
#include <cstdint>
#include <deque>
#include <functional>
#include <mutex>
#include <thread>
#include <unordered_map>
#include <unordered_set>
#include <vector>

extern "C" {
typedef void (*mxt_fn)(void *arg);
}

namespace mxt {

struct Opr;

struct VarState {
  // FIFO of ops waiting for this var; bool = wants write access.
  std::deque<std::pair<Opr *, bool>> queue;
  int active_readers = 0;
  bool active_writer = false;
  uint64_t version = 0;  // bumped on every completed write
};

struct Opr {
  std::function<void()> fn;
  std::vector<int64_t> reads, writes;
  int wait = 0;  // var grants still outstanding
};

class Engine {
 public:
  explicit Engine(int nthreads) {
    if (nthreads < 1) nthreads = 1;
    for (int i = 0; i < nthreads; ++i)
      workers_.emplace_back([this] { WorkerLoop(); });
  }

  ~Engine() {
    WaitAll();
    {
      std::unique_lock<std::mutex> lk(mu_);
      shutdown_ = true;
      ready_cv_.notify_all();
    }
    for (auto &t : workers_) t.join();
  }

  int64_t NewVar() {
    std::unique_lock<std::mutex> lk(mu_);
    int64_t id = next_var_++;
    vars_.emplace(id, VarState{});
    return id;
  }

  void Push(std::function<void()> fn, const int64_t *reads, int nr,
            const int64_t *writes, int nw) {
    auto *op = new Opr();
    op->fn = std::move(fn);
    // dedupe; a var both read and written is a write (reference rule)
    std::unordered_set<int64_t> w(writes, writes + nw), r;
    for (int i = 0; i < nr; ++i)
      if (!w.count(reads[i])) r.insert(reads[i]);
    op->reads.assign(r.begin(), r.end());
    op->writes.assign(w.begin(), w.end());

    std::unique_lock<std::mutex> lk(mu_);
    ++outstanding_;
    op->wait = 0;
    for (int64_t v : op->reads)
      if (!TryGrant(v, op, false)) ++op->wait;
    for (int64_t v : op->writes)
      if (!TryGrant(v, op, true)) ++op->wait;
    if (op->wait == 0) Enqueue(op);
  }

  void WaitForVar(int64_t var) {
    // reference semantics: push a read op on the var, block on it
    std::mutex m;
    std::condition_variable cv;
    bool done = false;
    Push(
        [&] {
          std::unique_lock<std::mutex> lk(m);
          done = true;
          cv.notify_all();
        },
        &var, 1, nullptr, 0);
    std::unique_lock<std::mutex> lk(m);
    cv.wait(lk, [&] { return done; });
  }

  void WaitAll() {
    std::unique_lock<std::mutex> lk(mu_);
    drain_cv_.wait(lk, [this] { return outstanding_ == 0; });
  }

  uint64_t Version(int64_t var) {
    std::unique_lock<std::mutex> lk(mu_);
    auto it = vars_.find(var);
    return it == vars_.end() ? 0 : it->second.version;
  }

 private:
  // mu_ held.  Returns true if access granted immediately.
  bool TryGrant(int64_t v, Opr *op, bool write) {
    VarState &st = vars_[v];
    if (write) {
      if (!st.active_writer && st.active_readers == 0 && st.queue.empty()) {
        st.active_writer = true;
        return true;
      }
    } else {
      if (!st.active_writer && st.queue.empty()) {
        ++st.active_readers;
        return true;
      }
    }
    st.queue.emplace_back(op, write);
    return false;
  }

  // mu_ held.
  void Enqueue(Opr *op) {
    ready_.push_back(op);
    ready_cv_.notify_one();
  }

  // mu_ held.  Release op's grant on v, wake queued successors.
  void Release(int64_t v, bool write) {
    VarState &st = vars_[v];
    if (write) {
      st.active_writer = false;
      ++st.version;
    } else {
      --st.active_readers;
    }
    while (!st.queue.empty()) {
      auto [next, nw] = st.queue.front();
      if (nw) {
        if (st.active_writer || st.active_readers > 0) break;
        st.active_writer = true;
      } else {
        if (st.active_writer) break;
        ++st.active_readers;
      }
      st.queue.pop_front();
      if (--next->wait == 0) Enqueue(next);
      if (nw) break;  // writer granted exclusively; stop draining
    }
  }

  void WorkerLoop() {
    for (;;) {
      Opr *op;
      {
        std::unique_lock<std::mutex> lk(mu_);
        ready_cv_.wait(lk, [this] { return shutdown_ || !ready_.empty(); });
        if (shutdown_ && ready_.empty()) return;
        op = ready_.front();
        ready_.pop_front();
      }
      op->fn();
      {
        std::unique_lock<std::mutex> lk(mu_);
        for (int64_t v : op->reads) Release(v, false);
        for (int64_t v : op->writes) Release(v, true);
        if (--outstanding_ == 0) drain_cv_.notify_all();
      }
      delete op;
    }
  }

  std::mutex mu_;
  std::condition_variable ready_cv_, drain_cv_;
  std::deque<Opr *> ready_;
  std::unordered_map<int64_t, VarState> vars_;
  std::vector<std::thread> workers_;
  int64_t next_var_ = 1;
  int outstanding_ = 0;
  bool shutdown_ = false;
};

}  // namespace mxt

extern "C" {

void *MXTEngineCreate(int nthreads) { return new mxt::Engine(nthreads); }

void MXTEngineDestroy(void *h) { delete static_cast<mxt::Engine *>(h); }

int64_t MXTEngineNewVar(void *h) {
  return static_cast<mxt::Engine *>(h)->NewVar();
}

void MXTEnginePush(void *h, mxt_fn fn, void *arg, const int64_t *reads,
                   int nr, const int64_t *writes, int nw) {
  static_cast<mxt::Engine *>(h)->Push([fn, arg] { fn(arg); }, reads, nr,
                                      writes, nw);
}

void MXTEngineWaitForVar(void *h, int64_t var) {
  static_cast<mxt::Engine *>(h)->WaitForVar(var);
}

void MXTEngineWaitAll(void *h) { static_cast<mxt::Engine *>(h)->WaitAll(); }

uint64_t MXTEngineVarVersion(void *h, int64_t var) {
  return static_cast<mxt::Engine *>(h)->Version(var);
}

// internal-use hook for other translation units (prefetcher)
void MXTEnginePushStd(void *h, std::function<void()> *fn,
                      const int64_t *reads, int nr, const int64_t *writes,
                      int nw) {
  static_cast<mxt::Engine *>(h)->Push(std::move(*fn), reads, nr, writes, nw);
  delete fn;
}

}  // extern "C"
