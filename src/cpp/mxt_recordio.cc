// Native RecordIO reader + pooled buffer allocator + batch prefetcher.
//
// Reference analogs (SURVEY §2.1/§2.5):
//   * RecordIO layout:  3rdparty/dmlc-core/include/dmlc/recordio.h —
//     [kMagic:u32][cflag<<29|len:u32][payload][pad4] per part, multi-part
//     records chained via cflag 1/2/3.  Byte-compatible with the python
//     module (mxnet_tpu/recordio.py) and the reference's im2rec output.
//   * Pooled allocator:  src/storage/pooled_storage_manager.h — power-of-2
//     size-class freelists so steady-state batch reads never hit malloc.
//   * Prefetcher:  src/io/iter_prefetcher.h + dmlc ThreadedIter — batch
//     jobs are pushed to the dependency engine (mxt_engine.cc) with a
//     write-var per slot; completed batches are consumed FIFO.
//
// All file reads use pread(2): no shared seek state, so one reader handle
// serves every engine worker concurrently.

#include <fcntl.h>
#include <unistd.h>

#include <condition_variable>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <deque>
#include <functional>
#include <map>
#include <mutex>
#include <string>
#include <vector>

extern "C" void MXTEnginePushStd(void *, std::function<void()> *,
                                 const int64_t *, int, const int64_t *, int);
extern "C" int64_t MXTEngineNewVar(void *);

namespace mxt {

static const uint32_t kMagic = 0xced7230a;

// ---------------------------------------------------------------- pool ----
class BufferPool {
 public:
  static BufferPool &Get() {
    static BufferPool pool;
    return pool;
  }

  void *Alloc(size_t size) {
    int cls = SizeClass(size);
    {
      std::unique_lock<std::mutex> lk(mu_);
      auto &fl = free_[cls];
      if (!fl.empty()) {
        void *p = fl.back();
        fl.pop_back();
        ++hits_;
        return p;
      }
      ++misses_;
    }
    return std::malloc(size_t(1) << cls);
  }

  void Free(void *p, size_t size) {
    int cls = SizeClass(size);
    std::unique_lock<std::mutex> lk(mu_);
    auto &fl = free_[cls];
    if (fl.size() < kMaxPerClass) {
      fl.push_back(p);
      return;
    }
    lk.unlock();
    std::free(p);
  }

  void Stats(int64_t *hits, int64_t *misses) {
    std::unique_lock<std::mutex> lk(mu_);
    *hits = hits_;
    *misses = misses_;
  }

 private:
  static int SizeClass(size_t size) {
    int cls = 6;  // min 64B
    while ((size_t(1) << cls) < size) ++cls;
    return cls;
  }

  static const size_t kMaxPerClass = 16;
  std::mutex mu_;
  std::vector<void *> free_[48];
  int64_t hits_ = 0, misses_ = 0;
};

// -------------------------------------------------------------- reader ----
struct Rec {
  int64_t offset;  // of first part header
  int64_t size;    // total payload bytes (parts joined)
};

class RecordReader {
 public:
  // returns nullptr + error message on failure
  static RecordReader *Open(const char *path, std::string *err) {
    int fd = ::open(path, O_RDONLY);
    if (fd < 0) {
      *err = "cannot open " + std::string(path);
      return nullptr;
    }
    auto *r = new RecordReader(fd);
    if (!r->BuildIndex(err)) {
      delete r;
      return nullptr;
    }
    return r;
  }

  ~RecordReader() { ::close(fd_); }

  int64_t Count() const { return int64_t(recs_.size()); }

  bool InRange(int64_t i) const {
    return i >= 0 && size_t(i) < recs_.size();
  }

  int64_t Size(int64_t i) const {
    return InRange(i) ? recs_[size_t(i)].size : -1;
  }

  int64_t Offset(int64_t i) const {
    return InRange(i) ? recs_[size_t(i)].offset : -1;
  }

  // read record i into out (caller sizes it via Size); true on success
  bool Read(int64_t i, uint8_t *out) const {
    if (!InRange(i)) return false;
    const Rec &rec = recs_[size_t(i)];
    int64_t off = rec.offset;
    uint8_t *dst = out;
    for (;;) {
      uint32_t hdr[2];
      if (::pread(fd_, hdr, 8, off) != 8) return false;
      if (hdr[0] != kMagic) return false;
      uint32_t cflag = hdr[1] >> 29, len = hdr[1] & ((1u << 29) - 1);
      if (::pread(fd_, dst, len, off + 8) != ssize_t(len)) return false;
      dst += len;
      off += 8 + ((len + 3) & ~3u);
      if (cflag == 0 || cflag == 3) return true;
    }
  }

 private:
  explicit RecordReader(int fd) : fd_(fd) {}

  bool BuildIndex(std::string *err) {
    int64_t fsize = ::lseek(fd_, 0, SEEK_END);
    int64_t off = 0;
    while (off + 8 <= fsize) {
      int64_t start = off, total = 0;
      for (;;) {
        uint32_t hdr[2];
        if (::pread(fd_, hdr, 8, off) != 8) {
          *err = "truncated record header";
          return false;
        }
        if (hdr[0] != kMagic) {
          *err = "bad magic at offset " + std::to_string(off);
          return false;
        }
        uint32_t cflag = hdr[1] >> 29, len = hdr[1] & ((1u << 29) - 1);
        total += len;
        off += 8 + ((len + 3) & ~3u);
        if (cflag == 0 || cflag == 3) break;
        if (off + 8 > fsize) {
          *err = "truncated multi-part record";
          return false;
        }
      }
      recs_.push_back({start, total});
    }
    return true;
  }

  int fd_;
  std::vector<Rec> recs_;
};

// ---------------------------------------------------------- prefetcher ----
// A scheduled batch = one engine op: pread every record of the batch into
// one pooled buffer (concatenated, with an offsets table).  Slot write-vars
// bound how many batches EXECUTE concurrently; completed batches buffer in
// done_ until consumed, so total memory is paced by the CALLER keeping
// scheduled-consumed small (ImageRecordIter schedules capacity+1 ahead) —
// same contract as iter_prefetcher.h's bounded queue with a free-running
// producer.  Consumption is FIFO in schedule order.
struct Batch {
  uint8_t *data = nullptr;
  int64_t *offsets = nullptr;  // n+1 entries
  int64_t n = 0;
  int64_t bytes = 0;
  bool ok = true;
};

class Prefetcher {
 public:
  Prefetcher(RecordReader *reader, void *engine, int capacity)
      : reader_(reader), engine_(engine),
        capacity_(capacity < 1 ? 1 : capacity) {
    for (int i = 0; i < capacity_; ++i)
      slot_vars_.push_back(MXTEngineNewVar(engine_));
  }

  // caller must have drained the engine (wait_all) first
  ~Prefetcher() {
    std::unique_lock<std::mutex> lk(mu_);
    for (auto &kv : done_) FreeBatch(kv.second);
    done_.clear();
  }

  static void FreeBatch(const Batch &b) {
    BufferPool::Get().Free(b.data, size_t(b.bytes ? b.bytes : 1));
    BufferPool::Get().Free(b.offsets, (size_t(b.n) + 1) * sizeof(int64_t));
  }

  void Schedule(const int64_t *indices, int n) {
    std::vector<int64_t> idx(indices, indices + n);
    int64_t slot_var, seq;
    {
      std::unique_lock<std::mutex> lk(mu_);
      slot_var = slot_vars_[size_t(next_slot_++ % capacity_)];
      seq = scheduled_++;
    }
    auto *fn = new std::function<void()>([this, seq,
                                          idx = std::move(idx)] {
      Batch b;
      b.n = int64_t(idx.size());
      int64_t total = 0;
      for (int64_t i : idx) {
        if (!reader_->InRange(i)) { b.ok = false; continue; }
        total += reader_->Size(i);
      }
      b.bytes = total;
      b.data = static_cast<uint8_t *>(BufferPool::Get().Alloc(
          size_t(total) ? size_t(total) : 1));
      b.offsets = static_cast<int64_t *>(
          BufferPool::Get().Alloc((size_t(b.n) + 1) * sizeof(int64_t)));
      int64_t off = 0;
      for (int64_t j = 0; j < b.n; ++j) {
        b.offsets[j] = off;
        if (!reader_->InRange(idx[size_t(j)])) { b.ok = false; continue; }
        if (!reader_->Read(idx[size_t(j)], b.data + off)) b.ok = false;
        off += reader_->Size(idx[size_t(j)]);
      }
      b.offsets[b.n] = off;
      std::unique_lock<std::mutex> lk(mu_);
      done_.emplace(seq, b);
      cv_.notify_all();
    });
    // write-dep on the slot var serializes reuse of the same slot while
    // distinct slots run in parallel across engine workers
    MXTEnginePushStd(engine_, fn, nullptr, 0, &slot_var, 1);
  }

  // blocks; batches come out in SCHEDULE order (reference ThreadedIter
  // contract) regardless of completion order across slots.  Returns false
  // if every scheduled batch was already consumed.
  bool Next(Batch *out) {
    std::unique_lock<std::mutex> lk(mu_);
    if (consumed_ == scheduled_) return false;
    int64_t want = consumed_;
    cv_.wait(lk, [&] { return done_.count(want) != 0; });
    *out = done_[want];
    done_.erase(want);
    ++consumed_;
    return true;
  }

 private:
  RecordReader *reader_;
  void *engine_;
  int capacity_;
  std::vector<int64_t> slot_vars_;
  std::mutex mu_;
  std::condition_variable cv_;
  std::map<int64_t, Batch> done_;
  int64_t next_slot_ = 0, scheduled_ = 0, consumed_ = 0;
};

}  // namespace mxt

// ---------------------------------------------------------------- C ABI ----
extern "C" {

static thread_local std::string mxt_last_error;

const char *MXTGetLastError() { return mxt_last_error.c_str(); }

void *MXTRecordReaderCreate(const char *path) {
  std::string err;
  mxt::RecordReader *r = mxt::RecordReader::Open(path, &err);
  if (!r) mxt_last_error = err;
  return r;
}

void MXTRecordReaderDestroy(void *h) {
  delete static_cast<mxt::RecordReader *>(h);
}

int64_t MXTRecordReaderCount(void *h) {
  return static_cast<mxt::RecordReader *>(h)->Count();
}

int64_t MXTRecordReaderSize(void *h, int64_t i) {
  return static_cast<mxt::RecordReader *>(h)->Size(i);
}

int64_t MXTRecordReaderOffset(void *h, int64_t i) {
  return static_cast<mxt::RecordReader *>(h)->Offset(i);
}

int MXTRecordReaderRead(void *h, int64_t i, uint8_t *out) {
  return static_cast<mxt::RecordReader *>(h)->Read(i, out) ? 0 : -1;
}

void *MXTPrefetcherCreate(void *reader, void *engine, int capacity) {
  return new mxt::Prefetcher(static_cast<mxt::RecordReader *>(reader),
                             engine, capacity);
}

void MXTPrefetcherDestroy(void *h) {
  delete static_cast<mxt::Prefetcher *>(h);
}

void MXTPrefetcherSchedule(void *h, const int64_t *indices, int n) {
  static_cast<mxt::Prefetcher *>(h)->Schedule(indices, n);
}

int MXTPrefetcherNext(void *h, uint8_t **data, int64_t **offsets,
                      int64_t *n, int64_t *bytes) {
  mxt::Batch b;
  if (!static_cast<mxt::Prefetcher *>(h)->Next(&b)) return -1;
  if (!b.ok) {
    mxt::Prefetcher::FreeBatch(b);
    mxt_last_error = "record read failed";
    return -2;
  }
  *data = b.data;
  *offsets = b.offsets;
  *n = b.n;
  *bytes = b.bytes;
  return 0;
}

void MXTBatchFree(uint8_t *data, int64_t *offsets, int64_t n,
                  int64_t bytes) {
  mxt::BufferPool::Get().Free(data, size_t(bytes ? bytes : 1));
  mxt::BufferPool::Get().Free(offsets, (size_t(n) + 1) * sizeof(int64_t));
}

void MXTPoolStats(int64_t *hits, int64_t *misses) {
  mxt::BufferPool::Get().Stats(hits, misses);
}

}  // extern "C"
